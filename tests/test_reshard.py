"""Elastic resume: mesh-shape-agnostic checkpoint reshard (ROADMAP item 4).

Fast tier: the sharding resolver (path-based specs must equal the live
trainer spec trees, coverage-validated), cross-mesh restore bit-equality
(sharded TP/FSDP, legacy single-file, torn-checkpoint fallback), the
offline repartitioner, the ``load_latest`` shardings regression
(satellite 1), and serving loads of trainer checkpoints at a different
TP degree (token-identical).

Slow tier (``@slow @crash``): the cross-topology kill matrix — SIGKILL a
real LM run on mesh (4,1,2) at a checkpoint hazard, resume the SAME save
dir on (4,1,2)/(2,1,2)/(8,1,1); the logged loss series must be bit-equal
to an unpreempted control on the unchanged topology and equal up to
cross-topology reduction order (~1 ulp/step) on the changed ones —
ANALYSIS.md "Elastic topology & reshard" documents that boundary.
"""

import json
import os
import shutil
import signal
import subprocess
import sys

import jax
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from pytorch_distributed_tpu import reshard
from pytorch_distributed_tpu.models.transformer import tiny_config
from pytorch_distributed_tpu.ops.optim import build_optimizer
from pytorch_distributed_tpu.parallel import make_mesh
from pytorch_distributed_tpu.parallel.mesh import specs_to_shardings
from pytorch_distributed_tpu.resilience.faults import ENV_PLAN, FaultPlan, FaultSpec
from pytorch_distributed_tpu.train.lm import create_lm_state, shard_lm_state
from pytorch_distributed_tpu.utils.checkpoint import (
    Checkpointer,
    ManifestReader,
    _tree_paths,
    gather_global,
    save_checkpoint,
    save_sharded,
    validate_checkpoint,
)

TP_CFG = dict(attention="dense", model_axis="model", tp_size=2, dropout=0.0)


def tp_state(seed=0):
    cfg = tiny_config(**TP_CFG)
    tx = build_optimizer("adamw", 1e-2)
    return cfg, tx, create_lm_state(cfg, tx, jax.random.key(seed))


def mesh_of(devices8, dp, sp, mp):
    return make_mesh(devices8[: dp * sp * mp], data_parallel=dp,
                     seq_parallel=sp, model_parallel=mp)


def payload_on(mesh, cfg, tx, state, fsdp=True, step=3):
    placed, specs = shard_lm_state(mesh, state, cfg, fsdp=fsdp)
    return {"state": placed, "epoch": 1, "step": step, "best_ppl": 9.5}, specs


def trees_bit_equal(a, b):
    for (pa, la), (_, lb) in zip(
        jax.tree_util.tree_leaves_with_path(a),
        jax.tree_util.tree_leaves_with_path(b),
    ):
        la = np.asarray(jax.device_get(la))
        lb = np.asarray(jax.device_get(lb))
        assert la.shape == lb.shape, jax.tree_util.keystr(pa)
        assert np.array_equal(la, lb), jax.tree_util.keystr(pa)


def target_shardings(devices8, dp, sp, mp, cfg, tx, fsdp=True, seed=7):
    """(mesh, template payload, shardings payload) for a fresh trainer
    booting on the target topology — state template is a freshly
    initialized (different-seed) state, like a real resume."""
    mesh = mesh_of(devices8, dp, sp, mp)
    state = create_lm_state(cfg, tx, jax.random.key(seed))
    specs = reshard.resolve_lm_state_specs(state, mesh, cfg, fsdp=fsdp)
    template = {"state": state, "epoch": 0, "step": 0, "best_ppl": 0.0}
    return mesh, template, reshard.payload_shardings(mesh, template, specs)


# ---------------------------------------------------------------------------
# resolver


def test_manifest_specs_match_live_spec_tree(tmp_path, devices8):
    """Path-based resolution (what the offline CLI uses) must agree with
    the live spec builders on EVERY leaf — params, optimizer moments,
    FSDP overlay included."""
    cfg, tx, state = tp_state()
    mesh = mesh_of(devices8, 4, 1, 2)
    payload, _ = payload_on(mesh, cfg, tx, state, fsdp=True)
    save_sharded(tmp_path / "ck", payload)

    live = reshard.resolve_lm_state_specs(state, mesh, cfg, fsdp=True)
    paths, leaves, _ = _tree_paths({"state": live})
    live_map = dict(zip(paths, leaves))

    manifest = ManifestReader(tmp_path / "ck").manifest
    specs = reshard.manifest_specs(
        manifest, {"data": 4, "seq": 1, "model": 2}, config=cfg, fsdp=True
    )
    checked = 0
    for path, spec in specs.items():
        if path in ("epoch", "step", "best_ppl"):
            assert spec == P()
            continue
        live_spec = live_map[path]
        if isinstance(live_spec, P):
            assert tuple(spec) == tuple(live_spec), path
            checked += 1
    assert checked > 40  # params + mu + nu actually compared


def test_resolver_coverage_green():
    """The lint-time proof that rule-derived reshard targets are
    complete: partition coverage over the real probe trees."""
    reshard.assert_rules_cover()


def test_block_layout_arithmetic():
    ms = {"data": 4, "seq": 1, "model": 2}
    # one dim sharded over model -> 2 blocks
    assert reshard.block_layout((8, 6), P(None, "model"), ms) == [
        ((0, 8), (0, 3)), ((0, 8), (3, 6)),
    ]
    # tuple axes multiply; replicated dims don't split
    assert len(reshard.block_layout((8, 8), P(("data", "model"), None), ms)) == 8
    # scalars: one empty-bounds block
    assert reshard.block_layout((), P(), ms) == [()]
    with pytest.raises(ValueError):
        reshard.block_layout((6,), P("data"), ms)  # 6 % 4 != 0


# ---------------------------------------------------------------------------
# cross-mesh restore


def test_cross_mesh_restore_bit_equal(tmp_path, devices8):
    """A (4,1,2) TP+FSDP checkpoint restores bit-equal onto (2,1,2)
    TP+FSDP and onto (8,1,1) plain-DP — optimizer moments, scalars and
    host extras included — with the reshard surfaced in RestoreInfo."""
    cfg, tx, state = tp_state()
    mesh_a = mesh_of(devices8, 4, 1, 2)
    payload, _ = payload_on(mesh_a, cfg, tx, state, fsdp=True)
    save_sharded(tmp_path / "ck", payload)

    for (dp, sp, mp), fsdp in [((2, 1, 2), True), ((8, 1, 1), False)]:
        cfg_t = cfg if mp > 1 else tiny_config(
            attention="dense", model_axis=None, tp_size=1, dropout=0.0
        )
        mesh_b, template, shardings = target_shardings(
            devices8, dp, sp, mp, cfg_t, tx, fsdp=fsdp
        )
        back, info = reshard.load_elastic(
            tmp_path / "ck", template, shardings, mesh=mesh_b
        )
        assert info.resharded and info.format == "sharded"
        assert info.source_mesh["shape"] == [4, 1, 2]
        assert info.assembled_regions > 0  # layouts genuinely differ
        trees_bit_equal(payload["state"].params, back["state"].params)
        trees_bit_equal(payload["state"].opt_state, back["state"].opt_state)
        assert back["epoch"] == 1 and back["step"] == 3
        assert back["best_ppl"] == 9.5
        # the restored leaves really live on the TARGET mesh
        wte = back["state"].params["wte"]["embedding"]
        assert wte.sharding.mesh.shape["data"] == dp


def test_same_mesh_restore_takes_exact_path(tmp_path, devices8):
    """Unchanged topology: every region is a zero-copy exact block match
    and the restore is NOT flagged as a reshard."""
    cfg, tx, state = tp_state()
    mesh = mesh_of(devices8, 4, 1, 2)
    payload, _ = payload_on(mesh, cfg, tx, state, fsdp=True)
    save_sharded(tmp_path / "ck", payload)
    _, template, shardings = target_shardings(devices8, 4, 1, 2, cfg, tx)
    back, info = reshard.load_elastic(
        tmp_path / "ck", template, shardings, mesh=mesh
    )
    assert not info.resharded
    assert info.assembled_regions == 0 and info.exact_blocks > 0
    trees_bit_equal(payload["state"].params, back["state"].params)


def test_reshard_refused_when_disabled(tmp_path, devices8):
    cfg, tx, state = tp_state()
    payload, _ = payload_on(mesh_of(devices8, 4, 1, 2), cfg, tx, state)
    save_sharded(tmp_path / "ck", payload)
    mesh_b, template, shardings = target_shardings(
        devices8, 2, 1, 2, cfg, tx
    )
    with pytest.raises(reshard.ReshardRefused):
        reshard.load_elastic(tmp_path / "ck", template, shardings,
                             mesh=mesh_b, allow_reshard=False)
    # same topology is never refused
    mesh_a, template_a, shardings_a = target_shardings(
        devices8, 4, 1, 2, cfg, tx
    )
    reshard.load_elastic(tmp_path / "ck", template_a, shardings_a,
                         mesh=mesh_a, allow_reshard=False)


def test_legacy_single_file_cross_layout(tmp_path, devices8):
    """A legacy msgpack single-file checkpoint (the pre-sharded
    interchange format) restores onto a TP/FSDP mesh it never knew
    about, leaves placed slice-wise on the target."""
    cfg, tx, state = tp_state()
    mesh_a = mesh_of(devices8, 4, 1, 2)
    payload, _ = payload_on(mesh_a, cfg, tx, state, fsdp=True)
    legacy = {"state": gather_global(payload["state"]), "epoch": 1,
              "step": 3, "best_ppl": 9.5}
    save_checkpoint(tmp_path / "latest.ckpt", legacy)

    mesh_b, template, shardings = target_shardings(
        devices8, 2, 1, 2, cfg, tx
    )
    back, info = reshard.load_elastic(
        tmp_path / "latest.ckpt", template, shardings, mesh=mesh_b
    )
    assert info.format == "legacy"
    trees_bit_equal(payload["state"].params, back["state"].params)
    wte = back["state"].params["wte"]["embedding"]
    assert isinstance(wte, jax.Array)
    assert wte.sharding.mesh.shape["data"] == 2


def test_torn_fallback_composes_with_reshard(tmp_path, devices8):
    """The resilience fall-through (restorable_paths scanning past torn
    checkpoints) must hand the reshard path its older candidate: newest
    step checkpoint torn -> the previous one restores onto a DIFFERENT
    mesh."""
    cfg, tx, state = tp_state()
    mesh_a = mesh_of(devices8, 4, 1, 2)
    ck = Checkpointer(str(tmp_path))
    for step in (1, 2):
        placed, _ = shard_lm_state(mesh_a, state, cfg, fsdp=True)
        placed = placed.replace(step=np.int32(step))
        ck.save_step_sharded(
            {"state": placed, "epoch": 0, "step": step, "best_ppl": 1.0},
            step, block=True,
        )
    newest = ck.step_checkpoints()[-1][1]
    shard = next(
        os.path.join(newest, f) for f in os.listdir(newest)
        if f.startswith("shard-")
    )
    blob = open(shard, "rb").read()
    with open(shard, "wb") as f:
        f.write(blob[: len(blob) // 2])  # truncate: zip tail gone
    assert validate_checkpoint(newest) != []

    candidates = ck.restorable_paths()
    assert len(candidates) == 1  # the torn one was discarded
    mesh_b, template, shardings = target_shardings(
        devices8, 2, 1, 2, cfg, tx
    )
    back, info = reshard.load_elastic(
        candidates[0], template, shardings, mesh=mesh_b
    )
    assert info.resharded
    assert int(np.asarray(jax.device_get(back["state"].step))) == 1


def test_load_latest_forwards_shardings(tmp_path, devices8):
    """Satellite: ``Checkpointer.load_latest`` used to silently drop the
    ``shardings`` argument its siblings (load_latest_sharded/load_best)
    accept — callers got full-host numpy instead of placed arrays."""
    cfg, tx, state = tp_state()
    mesh = mesh_of(devices8, 4, 1, 2)
    payload, specs = payload_on(mesh, cfg, tx, state, fsdp=True)
    ck = Checkpointer(str(tmp_path))
    ck.save_latest_sharded(payload)

    template = {"state": state, "epoch": 0, "step": 0, "best_ppl": 0.0}
    shardings = reshard.payload_shardings(mesh, template, specs)
    back = ck.load_latest(template, shardings)
    wte = back["state"].params["wte"]["embedding"]
    assert isinstance(wte, jax.Array)
    assert wte.sharding == shardings["state"].params["wte"]["embedding"]
    # without shardings: the legacy-compatible full-numpy behavior
    back_np = ck.load_latest(template)
    assert isinstance(back_np["state"].params["wte"]["embedding"],
                      np.ndarray)


# ---------------------------------------------------------------------------
# offline repartition


def test_offline_repartition_roundtrip(tmp_path, devices8):
    """scripts/reshard.py's engine: relayout (4,1,2)->(2,1,2) offline,
    then a restore on the target mesh takes the exact-block path on
    every region (that is the point of pre-resharding) and is
    bit-equal."""
    cfg, tx, state = tp_state()
    mesh_a = mesh_of(devices8, 4, 1, 2)
    payload, _ = payload_on(mesh_a, cfg, tx, state, fsdp=True)
    save_sharded(tmp_path / "src", payload)

    stats = reshard.repartition(
        tmp_path / "src", tmp_path / "dst",
        {"data": 2, "seq": 1, "model": 2}, config=cfg, fsdp=True,
        verify=True,
    )
    assert stats["verified"] and stats["leaves"] > 0
    assert validate_checkpoint(tmp_path / "dst") == []
    meta = reshard.checkpoint_mesh(tmp_path / "dst")
    assert dict(zip(meta["axes"], meta["shape"])) == {
        "data": 2, "seq": 1, "model": 2,
    }

    mesh_b, template, shardings = target_shardings(
        devices8, 2, 1, 2, cfg, tx
    )
    back, info = reshard.load_elastic(
        tmp_path / "dst", template, shardings, mesh=mesh_b
    )
    assert not info.resharded and info.assembled_regions == 0
    trees_bit_equal(payload["state"].params, back["state"].params)
    trees_bit_equal(payload["state"].opt_state, back["state"].opt_state)

    # refuses to clobber an existing checkpoint without overwrite
    with pytest.raises(FileExistsError):
        reshard.repartition(tmp_path / "src", tmp_path / "dst",
                            {"data": 2, "seq": 1, "model": 2}, config=cfg)


def test_repartition_legacy_source(tmp_path, devices8):
    """A legacy single-file checkpoint repartitions into a sharded
    block-table checkpoint for any topology."""
    cfg, tx, state = tp_state()
    mesh_a = mesh_of(devices8, 4, 1, 2)
    payload, _ = payload_on(mesh_a, cfg, tx, state, fsdp=False)
    legacy = {"state": gather_global(payload["state"]), "epoch": 1,
              "step": 3, "best_ppl": 9.5}
    save_checkpoint(tmp_path / "latest.ckpt", legacy)

    reshard.repartition(
        tmp_path / "latest.ckpt", tmp_path / "dst",
        {"data": 8, "seq": 1, "model": 1}, config=cfg, fsdp=True,
        verify=True,
    )
    assert validate_checkpoint(tmp_path / "dst") == []
    cfg1 = tiny_config(attention="dense", model_axis=None, tp_size=1,
                       dropout=0.0)
    mesh_b, template, shardings = target_shardings(
        devices8, 8, 1, 1, cfg1, tx, fsdp=True
    )
    back, _ = reshard.load_elastic(
        tmp_path / "dst", template, shardings, mesh=mesh_b
    )
    trees_bit_equal(payload["state"].params, back["state"].params)


# ---------------------------------------------------------------------------
# serving at a different TP degree


def test_serving_load_tp_degrees_token_identical(tmp_path, devices8):
    """A trainer checkpoint written at dp4xtp2 serves greedy-token-
    identically whether loaded at TP=1 (replicated) or TP=2 — the
    acceptance criterion for train->serve topology changes."""
    from pytorch_distributed_tpu.models.generate import generate, generate_tp

    cfg, tx, state = tp_state()
    mesh_a = mesh_of(devices8, 4, 1, 2)
    payload, _ = payload_on(mesh_a, cfg, tx, state, fsdp=True)
    save_sharded(tmp_path / "ck", payload)

    cfg1 = tiny_config(attention="dense", model_axis=None, tp_size=1,
                       dropout=0.0)
    params1, info1 = reshard.load_trainer_params(tmp_path / "ck", cfg1)
    assert info1.format == "sharded"
    trees_bit_equal(payload["state"].params, params1)

    mesh_tp = make_mesh(devices8[:2], data_parallel=1, seq_parallel=1,
                        model_parallel=2)
    params2, info2 = reshard.load_trainer_params(
        tmp_path / "ck", cfg, mesh=mesh_tp
    )
    qkv = params2["block0"]["attn"]["qkv"]["kernel"]
    assert isinstance(qkv, jax.Array)
    shard = next(iter(qkv.addressable_shards)).data.shape
    assert shard[2] == qkv.shape[2] // 2  # heads split over model axis

    prompt = np.arange(1, 9, dtype=np.int32)[None, :]
    rng = jax.random.key(0)
    out1 = np.asarray(generate(cfg1, params1, prompt, rng,
                               max_new_tokens=8))
    out2 = np.asarray(jax.device_get(generate_tp(
        mesh_tp, cfg, params2, prompt, rng, max_new_tokens=8
    )))
    np.testing.assert_array_equal(out1, out2)


def test_serving_load_shape_mismatch_raises(tmp_path, devices8):
    cfg, tx, state = tp_state()
    payload, _ = payload_on(mesh_of(devices8, 4, 1, 2), cfg, tx, state)
    save_sharded(tmp_path / "ck", payload)
    import dataclasses

    wrong = dataclasses.replace(
        tiny_config(attention="dense", model_axis=None, tp_size=1,
                    dropout=0.0),
        vocab_size=256,
    )
    with pytest.raises((ValueError, KeyError)):
        reshard.load_trainer_params(tmp_path / "ck", wrong)


# ---------------------------------------------------------------------------
# elastic resume at the trainer level (+ compilecache coverage, slow)


@pytest.mark.slow
def test_trainer_elastic_resume_and_registry_coverage(tmp_path, devices8):
    """An LMTrainer killed... actually: suspend-saved on (4,1,2), resumed
    by a fresh LMTrainer on (2,1,2): gstep/epoch/cursor/best_ppl carry
    over, training continues finitely, and the compile-cache coverage
    guard still accounts for every live program on the NEW mesh (no
    unpredicted compiles after an elastic resume — the trainers' half of
    satellite 2; the serving half is the warmup/cold-request contract
    proven in test_compilecache.py)."""
    from pytorch_distributed_tpu.data.tokens import SyntheticTokens
    from pytorch_distributed_tpu.train import LMTrainer, LMTrainerConfig

    def build(dp, mp, **over):
        cfg_m = tiny_config(attention="dense",
                            model_axis="model" if mp > 1 else None,
                            tp_size=mp, dropout=0.0)
        over.setdefault("epochs", 2)
        cfg = LMTrainerConfig(
            batch_size=8 // dp, lr=1e-2,
            save_dir=str(tmp_path), num_workers=0, log_every=0,
            seed=0, **over,
        )
        train = SyntheticTokens(size=16, seq_len=32, vocab_size=128)
        val = SyntheticTokens(size=8, seq_len=32, vocab_size=128, seed=9)
        return LMTrainer(cfg_m, train, val, cfg,
                         mesh=mesh_of(devices8, dp, 1, mp))

    t_a = build(4, 2, epochs=1)
    t_a.fit()
    t_a.ckpt.save_latest_sharded(t_a._payload_live(1, 0))
    gstep_a = int(np.asarray(jax.device_get(t_a.state.step)))
    assert gstep_a == 2  # 16 samples / global batch 8

    t_b = build(2, 2)
    assert t_b.try_resume()
    assert int(np.asarray(jax.device_get(t_b.state.step))) == gstep_a
    assert t_b.start_epoch == 1
    assert t_b.best_ppl == t_a.best_ppl
    trees_bit_equal(t_a.state.params, t_b.state.params)
    res = t_b.fit()  # epoch 1 on the new mesh
    assert np.isfinite(res["loss"])
    assert int(np.asarray(jax.device_get(t_b.state.step))) == 2 * gstep_a
    t_b.assert_registry_covers()  # no unpredicted programs post-reshard

    # elastic_resume=False refuses the mismatched checkpoint entirely
    t_c = build(8, 1, elastic_resume=False)
    assert not t_c.try_resume()


# ---------------------------------------------------------------------------
# the cross-topology kill matrix (slow): SIGKILL on (4,1,2), resume on
# three topologies, loss series vs an unpreempted control.
# scripts/ci_check.sh --reshard-smoke runs the image-trainer smoke below.

CHILD = os.path.join(os.path.dirname(__file__), "reshard_child.py")


def _run_lm_child(save_dir, mesh, env_extra=None, timeout=300):
    env = dict(os.environ)
    env.pop(ENV_PLAN, None)
    env.update(env_extra or {})
    return subprocess.run(
        [sys.executable, CHILD, "--save-dir", str(save_dir),
         "--mesh", mesh, "--fsdp"],
        env=env, capture_output=True, text=True, timeout=timeout,
    )


def _series(save_dir):
    with open(os.path.join(str(save_dir), "progress.jsonl")) as f:
        return [json.loads(line) for line in f]


@pytest.fixture(scope="module")
def killed_run(tmp_path_factory):
    """One SIGKILL'd (4,1,2) run + one unpreempted (4,1,2) control,
    shared by every matrix cell (each cell copies the killed dir)."""
    root = tmp_path_factory.mktemp("reshard_matrix")
    kill_dir, ctl_dir = root / "killed", root / "control"
    kill_dir.mkdir(), ctl_dir.mkdir()
    plan = FaultPlan([FaultSpec(site="ckpt.post_commit", kind="kill",
                                at=2)])
    r = _run_lm_child(kill_dir, "4,1,2", {ENV_PLAN: plan.to_json()})
    assert r.returncode == -signal.SIGKILL, (
        f"rc={r.returncode}\nstdout:{r.stdout}\nstderr:{r.stderr}"
    )
    assert not (kill_dir / "result.json").exists()
    rc = _run_lm_child(ctl_dir, "4,1,2")
    assert rc.returncode == 0, rc.stderr
    control = {r["gstep"]: r["loss"] for r in _series(ctl_dir)}
    assert sorted(control) == [1, 2, 3, 4, 5, 6]
    return kill_dir, control


@pytest.mark.slow
@pytest.mark.crash
@pytest.mark.parametrize(
    "target", ["4,1,2", "2,1,2", "8,1,1"],
    ids=["same-4x2", "shrink-2x2", "flatten-8x1"],
)
def test_kill_matrix_cross_topology_resume(tmp_path, killed_run, target):
    """Kill on (4,1,2); resume on ``target``. The pre-kill prefix and a
    same-topology resume must be BIT-equal to the unpreempted control
    series; a cross-topology resume matches it up to reduction order
    (the restore itself is bit-stable — proven by the fast tests — so
    any drift is the step's cross-topology sum associativity, not
    corruption)."""
    killed_dir, control = killed_run
    work = tmp_path / "resume"
    shutil.copytree(killed_dir, work)

    r = _run_lm_child(work, target)
    assert r.returncode == 0, (
        f"relaunch on {target} failed\nstdout:{r.stdout}\n"
        f"stderr:{r.stderr}"
    )
    result = json.load(open(work / "result.json"))
    assert result["resumed"], "run 2 must restore a checkpoint"
    assert result["final_step"] == 6  # 2 epochs x 3 steps, completed
    assert np.isfinite(result["val_loss"])
    if target != "4,1,2":
        assert "elastic resume" in r.stdout  # it really did reshard

    records = _series(work)
    pid2 = records[-1]["pid"]
    run1 = [r for r in records if r["pid"] != pid2]
    run2 = [r for r in records if r["pid"] == pid2]
    # monotonic, gap-free step coverage across the crash
    steps2 = [r["gstep"] for r in run2]
    assert steps2 == list(range(steps2[0], steps2[0] + len(steps2)))
    assert steps2[0] <= run1[-1]["gstep"] + 1
    assert {r["gstep"] for r in run1} | set(steps2) >= {1, 2, 3, 4, 5, 6}

    # pre-kill prefix: same topology as control -> bit-equal
    for r1 in run1:
        assert r1["loss"] == control[r1["gstep"]], r1
    # resumed segment: bit-equal on the unchanged topology; within
    # cross-topology reduction order (~ulp/step) on the changed ones
    for r2 in run2:
        if target == "4,1,2":
            assert r2["loss"] == control[r2["gstep"]], r2
        else:
            np.testing.assert_allclose(
                r2["loss"], control[r2["gstep"]], rtol=1e-4,
                err_msg=str(r2),
            )


@pytest.mark.slow
@pytest.mark.crash
def test_reshard_smoke_kill_and_cross_mesh_resume(tmp_path):
    """The ci_check --reshard-smoke cell: the IMAGE trainer (fast child)
    killed mid-save on (4,1,2), resumed on (2,1,2) at the same global
    batch — proves elastic resume end-to-end through the other trainer
    in one kill-and-resume cycle."""
    child = os.path.join(os.path.dirname(__file__), "crash_child.py")
    plan = FaultPlan([FaultSpec(site="ckpt.post_commit", kind="kill",
                                at=2)])
    env = dict(os.environ)
    env[ENV_PLAN] = plan.to_json()
    r1 = subprocess.run(
        [sys.executable, child, "--save-dir", str(tmp_path),
         "--mesh", "4,1,2", "--batch-size", "4"],
        env=env, capture_output=True, text=True, timeout=300,
    )
    assert r1.returncode == -signal.SIGKILL, (
        f"rc={r1.returncode}\nstderr:{r1.stderr}"
    )
    env2 = dict(os.environ)
    env2.pop(ENV_PLAN, None)
    r2 = subprocess.run(
        [sys.executable, child, "--save-dir", str(tmp_path),
         "--mesh", "2,1,2", "--batch-size", "8"],
        env=env2, capture_output=True, text=True, timeout=300,
    )
    assert r2.returncode == 0, (
        f"relaunch failed\nstdout:{r2.stdout}\nstderr:{r2.stderr}"
    )
    result = json.load(open(tmp_path / "result.json"))
    assert result["resumed"]
    assert result["final_step"] == 4  # 2 epochs x 2 steps at global 16
    assert np.isfinite(result["val_loss"])
    assert "elastic resume" in r2.stdout
