"""Mesh / sharding / collective semantics on 8 virtual CPU devices.

The key invariant (SURVEY.md §7): all three reference DP flavors are the
same SPMD program over different meshes, and 8-way data parallelism computes
the same update a single device would on the concatenated batch.
"""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_tpu.ops.optim import sgd_with_weight_decay
from pytorch_distributed_tpu.parallel import (
    batch_sharding,
    global_batch_size,
    local_replica_count,
    make_mesh,
    replicated_sharding,
    shard_batch,
    single_device_mesh,
)
from pytorch_distributed_tpu.parallel.collectives import all_reduce
from pytorch_distributed_tpu.train.state import TrainState
from pytorch_distributed_tpu.train.step import make_train_step


class TinyMLP(nn.Module):
    """BN-free model: DP gradient combine must be bit-comparable to the
    single-device gradient on the concatenated batch."""

    num_classes: int = 10

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(32)(x))
        return nn.Dense(self.num_classes)(x)


def _batch(n, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "image": rng.normal(size=(n, 8, 8, 3)).astype(np.float32),
        "label": (np.arange(n) % 10).astype(np.int32),
    }


def test_mesh_shapes(devices8):
    mesh = make_mesh(devices8)
    assert mesh.shape["data"] == 8 and mesh.shape["model"] == 1
    assert global_batch_size(mesh, 400) == 3200  # ref: bs 400 × 8 GPUs
    assert local_replica_count(mesh) == 8

    one = single_device_mesh()
    assert one.shape["data"] == 1
    assert local_replica_count(one) == 1

    mp = make_mesh(devices8, model_parallel=2)
    assert mp.shape["data"] == 4 and mp.shape["model"] == 2

    with pytest.raises(ValueError):
        make_mesh(devices8, data_parallel=3, model_parallel=2)


def test_shard_batch_layout(devices8):
    mesh = make_mesh(devices8)
    batch = shard_batch(mesh, _batch(16))
    assert batch["image"].shape == (16, 8, 8, 3)
    assert batch["image"].sharding == batch_sharding(mesh)
    np.testing.assert_array_equal(np.asarray(batch["label"]), _batch(16)["label"])


def test_dp_matches_single_device(devices8):
    """8-way DP step == single-device step on the concatenated batch (the
    DDP-averages-gradients contract, restnet_ddp.py:29)."""
    model = TinyMLP()
    tx = sgd_with_weight_decay(0.1, momentum=0.9, weight_decay=1e-4)

    def run(mesh, steps=3):
        state = TrainState.create(model, tx, jax.random.key(0), (1, 8, 8, 3))
        state = jax.device_put(state, replicated_sharding(mesh))
        step_fn = make_train_step(mesh)
        losses = []
        for i in range(steps):
            batch = shard_batch(mesh, _batch(32, seed=i))
            state, metrics = step_fn(state, batch)
            losses.append(float(metrics["loss"]))
        return state, losses

    state8, losses8 = run(make_mesh(devices8))
    state1, losses1 = run(single_device_mesh())

    np.testing.assert_allclose(losses8, losses1, rtol=1e-5)
    for a, b in zip(jax.tree.leaves(state8.params), jax.tree.leaves(state1.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)


def test_replicated_params_stay_identical(devices8):
    """Params remain replicated after steps (DDP's core invariant)."""
    mesh = make_mesh(devices8)
    model = TinyMLP()
    tx = sgd_with_weight_decay(0.1)
    state = TrainState.create(model, tx, jax.random.key(0), (1, 8, 8, 3))
    state = jax.device_put(state, replicated_sharding(mesh))
    step_fn = make_train_step(mesh)
    state, _ = step_fn(state, shard_batch(mesh, _batch(16)))
    leaf = jax.tree.leaves(state.params)[0]
    shards = [np.asarray(s.data) for s in leaf.addressable_shards]
    for s in shards[1:]:
        np.testing.assert_array_equal(shards[0], s)


def test_all_reduce_single_process():
    out = all_reduce({"a": np.float32(3.0)}, reduce="sum")
    assert float(out["a"]) == 3.0
    with pytest.raises(ValueError):
        all_reduce({"a": np.float32(1.0)}, reduce="median")
