"""Prefix-sharing KV cache (round 17 tentpole): allocator refcounts,
the radix PrefixIndex, copy-on-write admission, token-identity
prefix-on vs prefix-off (single replica, int8 pool, disaggregated
fleet, TP=2), shared blocks pinned through preemption, LRU eviction
under pool pressure, registry coverage of the COW program, the
kind="prefix" JSONL schema + report section, and the fleet satellites
(affinity LRU cap, prefix-sticky gate rung)."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_tpu.models.transformer import (
    TransformerLM,
    tiny_config,
)
from pytorch_distributed_tpu.serving import (
    BlockAllocator,
    PrefixIndex,
    Scheduler,
    blocks_needed_suffix,
)


def setup(max_seq_len=96, **over):
    cfg = tiny_config(attention="dense", max_seq_len=max_seq_len, **over)
    params = TransformerLM(cfg).init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return cfg, params


def drive(s, prompts, budgets, stagger=4):
    """Submit prompts with ``stagger`` ticks between arrivals (so
    earlier requests' blocks are indexed before later lookups), then
    drain; returns {rid: [tokens]} in submit order."""
    outs, rids = {}, []
    for p, b in zip(prompts, budgets):
        rids.append(s.submit(p, b))
        for _ in range(stagger):
            for rid, tok in s.step():
                outs.setdefault(rid, []).append(tok)
    for rid, toks in s.drain().items():
        outs.setdefault(rid, []).extend(toks)
    return {r: outs[r] for r in rids}


# ---------------------------------------------------------------------------
# allocator refcounts (pure host logic — fast tier)
# ---------------------------------------------------------------------------


def test_allocator_refcount_free_at_zero_and_double_free():
    a = BlockAllocator(8)
    chain = a.alloc(0, 3)
    assert chain == [1, 2, 3] and all(a.ref(b) == 1 for b in chain)
    a.incref(1)  # the index's claim
    a.free(0)
    # block 1 pinned by the extra ref; 2 and 3 freed
    assert a.ref(1) == 1 and a.ref(2) == 0 and a.available == 6
    assert a.shared_blocks == 0
    with pytest.raises(RuntimeError, match="double free"):
        a.decref(2)
    with pytest.raises(ValueError, match="dead block"):
        a.incref(2)
    a.decref(1)
    assert a.available == 7


def test_allocator_alloc_mixed_shares_and_pins():
    a = BlockAllocator(10)
    donor = a.alloc(0, 3)
    a.incref(donor[0]); a.incref(donor[1])  # noqa: E702 — index refs
    a.free(0)  # donor retires; 2 blocks survive as index-only
    mixed = a.alloc_mixed(1, donor[:2], 2)
    assert mixed[:2] == donor[:2]
    assert a.ref(donor[0]) == 2 and a.shared_blocks == 2
    assert a.fresh_allocated == 5 and a.shared_reused == 2
    # the sharer frees: shared blocks survive (index ref), fresh don't
    a.free(1)
    assert a.ref(donor[0]) == 1 and a.ref(mixed[2]) == 0
    # sharing a dead block is loud
    with pytest.raises(ValueError, match="cannot share"):
        a.alloc_mixed(2, [mixed[2]], 1)
    # all-or-nothing: OOM increfs NOTHING
    before = a.ref(donor[0])
    assert a.alloc_mixed(2, donor[:1], 99) is None
    assert a.ref(donor[0]) == before


def test_allocator_shared_chain_pinned_through_swap_free():
    """The PR 11 state machine composes with refcounts: a chain mid-swap
    still refuses to free, and when a swapped-out chain IS freed its
    shared blocks stay resident for the other holders."""
    a = BlockAllocator(10)
    c0 = a.alloc(0, 2)
    a.incref(c0[0])
    a.free(0)
    a.alloc_mixed(1, [c0[0]], 1)
    a.set_state(1, "swapping-out")
    with pytest.raises(RuntimeError, match="swapping-out"):
        a.free(1)
    a.clear_state(1)
    a.free(1)  # swap-out committed: chain decrefs...
    assert a.ref(c0[0]) == 1  # ...but the indexed block never left


def test_blocks_needed_suffix_matches_cold_at_zero():
    assert blocks_needed_suffix(0, 9, 20, 16, 16) == 2
    # prefill restarting at a covered boundary pads from THERE
    assert blocks_needed_suffix(16, 20, 2, 8, 8) == 3  # pad 16+8=24→3
    assert blocks_needed_suffix(16, 17, 30, 8, 8) == 6  # decode bound


# ---------------------------------------------------------------------------
# radix index (pure host logic — fast tier)
# ---------------------------------------------------------------------------


def test_prefix_index_insert_lookup_dedup_evict():
    a = BlockAllocator(16)
    idx = PrefixIndex(4, a)
    toks = np.arange(100, 120, dtype=np.int32)  # 5 full blocks of 4
    chain = a.alloc(0, 5)
    assert idx.insert(toks, chain, upto=12) == 3  # floors to full blocks
    assert len(idx) == 3 and all(a.ref(b) == 2 for b in chain[:3])
    # dedup: a second chain with the same prefix keeps the FIRST blocks
    other = a.alloc(1, 3)
    assert idx.insert(toks, other, upto=12) == 0
    assert a.ref(other[0]) == 1
    # lookup: longest full-block match, diverging token stops the walk
    assert idx.lookup(toks) == chain[:3]
    fork = toks.copy(); fork[5] += 1  # noqa: E702
    assert idx.lookup(fork) == chain[:1]
    assert idx.lookup(np.arange(50, 60, dtype=np.int32)) == []
    m = idx.metrics()
    assert m["prefix_hits"] == 2 and m["prefix_lookups"] == 3
    # eviction: chain-held blocks (ref 2) are pinned — nothing evictable
    assert idx.evict(3) == 0
    a.free(0); a.free(1)  # noqa: E702
    # now index-only (ref 1): leaves evict first, cascading to parents
    freed = idx.evict(2)
    assert freed == 2 and len(idx) == 1
    assert idx.lookup(toks) == chain[:1]  # the surviving root block
    assert idx.evict(5) == 1 and len(idx) == 0
    assert a.available == 15


def test_prefix_index_lru_prefers_oldest_leaf():
    a = BlockAllocator(16)
    idx = PrefixIndex(2, a)
    t1 = np.asarray([1, 2], np.int32)
    t2 = np.asarray([3, 4], np.int32)
    c1 = a.alloc(0, 1); idx.insert(t1, c1, 2); a.free(0)  # noqa: E702
    c2 = a.alloc(0, 1); idx.insert(t2, c2, 2); a.free(0)  # noqa: E702
    idx.lookup(t1)  # t1 is now the RECENT one
    assert idx.evict(1) == 1
    assert idx.lookup(t1) == c1 and idx.lookup(t2) == []


# ---------------------------------------------------------------------------
# token identity + accounting (tiny model — fast tier)
# ---------------------------------------------------------------------------


def _shared_prompts(cfg, prefix_len=24, tails=(5, 9, 3), seed=0):
    shared = np.arange(1, prefix_len + 1, dtype=np.int32)
    rng = np.random.default_rng(seed)
    return [
        np.concatenate([
            shared,
            rng.integers(1, cfg.vocab_size, (l,)).astype(np.int32),
        ])
        for l in tails
    ]


def test_prefix_on_off_token_identity_and_accounting():
    cfg, params = setup()
    # tail 8 → 32 tokens, a block multiple: its identical twin below is
    # a FULL-cover hit, the copy-on-write path
    prompts = _shared_prompts(cfg, tails=(8, 9, 3))
    prompts.append(prompts[0].copy())
    budgets = [6, 6, 6, 6]
    on = Scheduler(cfg, params, n_slots=3, block_len=8, prefill_chunk=16,
                   prefix_cache=True)
    off = Scheduler(cfg, params, n_slots=3, block_len=8, prefill_chunk=16)
    got_on = drive(on, prompts, budgets)
    got_off = drive(off, prompts, budgets)
    assert list(got_on.values()) == list(got_off.values())
    m_on, m_off = on.metrics(), off.metrics()
    assert m_on["prefix_hits"] >= 3
    assert m_on["prefix_cow_copies"] >= 1  # the identical prompt
    assert m_on["prefix_covered_tokens"] > 0
    # THE tentpole claim at test scale: shared-prefix admissions prefill
    # far fewer tokens than the no-sharing engine on the same work
    assert (m_on["admitted_prefill_tokens"]
            < m_off["admitted_prefill_tokens"])
    assert m_off["prefix_hits"] == 0 and not m_off["prefix_cache"]
    # retirement decrefs but the index retains: blocks in use == indexed
    assert on.engine.allocator.in_use == m_on["prefix_index_blocks"] > 0
    # teardown drops the index references too
    on.engine.release_all()
    assert on.engine.allocator.in_use == 0


def test_prefix_int8_pool_composes():
    """int8 pools share: block ids name the same rows in the quantized
    pools AND their fp32 scale siblings, so sharing/COW move both in
    lockstep — streams identical to the int8 no-sharing engine."""
    cfg, params = setup()
    prompts = _shared_prompts(cfg, tails=(8, 9, 3))
    prompts.append(prompts[0].copy())  # block-aligned twin → COW
    budgets = [6, 6, 6, 6]
    on = Scheduler(cfg, params, n_slots=3, block_len=8, prefill_chunk=16,
                   prefix_cache=True, kv_dtype="int8")
    off = Scheduler(cfg, params, n_slots=3, block_len=8, prefill_chunk=16,
                    kv_dtype="int8")
    assert list(drive(on, prompts, budgets).values()) == \
        list(drive(off, prompts, budgets).values())
    assert on.metrics()["prefix_hits"] >= 3
    assert on.metrics()["prefix_cow_copies"] >= 1


def test_prefix_covered_cap_keeps_padded_tail_in_bounds():
    """A near-full-length prompt's hit is CAPPED so the chunk-padded
    tail never scatters past max_seq_len (the table-slice safety
    bound) — and the capped admission still streams identically."""
    cfg, params = setup(max_seq_len=32)
    prompt = np.arange(1, 29, dtype=np.int32)  # 28 tokens
    on = Scheduler(cfg, params, n_slots=2, block_len=8, prefill_chunk=8,
                   prefix_cache=True)
    off = Scheduler(cfg, params, n_slots=2, block_len=8, prefill_chunk=8)
    prompts, budgets = [prompt, prompt.copy()], [4, 4]
    assert list(drive(on, prompts, budgets).values()) == \
        list(drive(off, prompts, budgets).values())
    m = on.metrics()
    # full-cover candidate covered=27 would pad to 35 > 32: the cap
    # drops it to the 24-token block boundary (3 shared blocks, no COW)
    assert m["prefix_hits"] >= 1
    assert m["prefix_covered_tokens"] == 24
    assert m["prefix_cow_copies"] == 0


def test_prefix_eviction_under_pool_pressure():
    """Index-only blocks are the first pool-pressure valve: a new
    admission that cannot get fresh blocks evicts LRU refcount-1 index
    blocks and proceeds — queueing (and the pressure tier) only engage
    when the index has nothing left to give."""
    cfg, params = setup()
    s = Scheduler(cfg, params, n_slots=1, n_blocks=8, block_len=8,
                  prefill_chunk=8, prefix_cache=True)
    r0 = s.submit(np.arange(1, 17, dtype=np.int32), 2)
    s.drain()
    assert s.metrics()["prefix_index_blocks"] >= 2
    r1 = s.submit(np.arange(40, 80, dtype=np.int32), 2)  # needs 6 blocks
    outs = s.drain()
    m = s.metrics()
    assert len(outs[r1]) == 2 and m["prefix_evictions"] >= 1
    assert r0 != r1


def test_prefix_shared_block_survives_preemption():
    """COW/refcount under the pressure tier: preempting (swap path) a
    chain that SHARES prefix blocks must not drag them — the other
    sharer and the index keep them resident, and every stream (victim
    included, restored) stays token-identical to the no-sharing,
    no-preemption engine."""
    cfg, params = setup()
    prompts = _shared_prompts(cfg, tails=(5, 7))
    budgets = [4, 8]

    on = Scheduler(cfg, params, n_slots=3, block_len=8, prefill_chunk=8,
                   prefix_cache=True, offload=True, swap_policy="swap",
                   protect_ticks=0)
    outs = {}
    rid_a = on.submit(prompts[0], budgets[0])
    for _ in range(8):  # a retires (4 chunks... then 4 tokens)
        for rid, tok in on.step():
            outs.setdefault(rid, []).append(tok)
    assert len(outs.get(rid_a, [])) == budgets[0]
    rid_b = on.submit(prompts[1], budgets[1])
    for _ in range(4):  # b hits the prefix, prefills, starts decoding
        for rid, tok in on.step():
            outs.setdefault(rid, []).append(tok)
    alloc = on.engine.allocator
    shared = [b for b in range(1, alloc.n_blocks) if alloc.ref(b) > 1]
    assert len(shared) >= 3  # b rides a's indexed prefix blocks
    assert on.preempt(rid_b, reason="test").choice == "swap"
    for _ in range(2):
        for rid, tok in on.step():
            outs.setdefault(rid, []).append(tok)
    # mid-park: the victim's free decref'd, the index still pins them
    for b in shared:
        assert alloc.ref(b) >= 1, f"shared block {b} was dragged"
    for rid, toks in on.drain().items():
        outs.setdefault(rid, []).extend(toks)
    m = on.metrics()
    assert m["preempts"] == 1 and m["restores"] == 1

    off = Scheduler(cfg, params, n_slots=3, block_len=8, prefill_chunk=8)
    ref = {}
    ra = off.submit(prompts[0], budgets[0])
    for _ in range(8):
        for rid, tok in off.step():
            ref.setdefault(rid, []).append(tok)
    rb = off.submit(prompts[1], budgets[1])
    for rid, toks in off.drain().items():
        ref.setdefault(rid, []).extend(toks)
    assert outs[rid_a] == ref[ra] and outs[rid_b] == ref[rb]


def test_prefix_recompute_restore_hits_own_prefix():
    """The recompute-restore re-prefill consults the index: a parked
    request whose prompt blocks are still retained re-prefills only its
    uncovered tail — and resumes bit-exact."""
    cfg, params = setup()
    prompts = _shared_prompts(cfg, tails=(5,))
    on = Scheduler(cfg, params, n_slots=2, block_len=8, prefill_chunk=8,
                   prefix_cache=True, offload=True,
                   swap_policy="recompute", protect_ticks=0)
    outs = {}
    rid = on.submit(prompts[0], 8)
    for _ in range(6):
        for r, tok in on.step():
            outs.setdefault(r, []).append(tok)
    hits_before = on.metrics()["prefix_hits"]
    assert on.preempt(rid, reason="test").choice == "recompute"
    for r, toks in on.drain().items():
        outs.setdefault(r, []).extend(toks)
    assert on.metrics()["prefix_hits"] > hits_before  # restore hit
    off = Scheduler(cfg, params, n_slots=2, block_len=8, prefill_chunk=8)
    roff = off.submit(prompts[0], 8)
    assert outs[rid] == off.drain()[roff]


# ---------------------------------------------------------------------------
# registry coverage (compilecache gate)
# ---------------------------------------------------------------------------


def test_prefix_registry_covers_cow_program():
    from pytorch_distributed_tpu.compilecache import serving_registry

    cfg, params = setup()
    prompts = _shared_prompts(cfg, tails=(8,))
    prompts.append(prompts[0].copy())  # forces the COW program
    on = Scheduler(cfg, params, n_slots=2, block_len=8, prefill_chunk=16,
                   prefix_cache=True)
    drive(on, prompts, [4, 4])
    assert on.metrics()["prefix_cow_copies"] >= 1
    names = on.engine.compiled_program_names()
    assert "kv_block_copy" in names
    reg = serving_registry(on.engine)
    reg.assert_covers(names)  # zero rogue programs incl. the hit path
    # a no-prefix engine predicts no COW program — and cannot run it
    off = Scheduler(cfg, params, n_slots=2, block_len=8, prefill_chunk=16)
    reg_off = serving_registry(off.engine)
    assert not reg_off.predicts("kv_block_copy")
    with pytest.raises(RuntimeError, match="prefix_cache"):
        off.engine.admit_shared(0, prompts[0], 4)
    with pytest.raises(RuntimeError, match="prefix_cache"):
        off.engine.warm_block_copy()
    # fingerprints must not be interchangeable across the flag
    assert reg.fingerprint != reg_off.fingerprint


def test_prefix_warm_block_copy_inert():
    from pytorch_distributed_tpu.compilecache import serving_registry

    cfg, params = setup()
    s = Scheduler(cfg, params, n_slots=2, block_len=8, prefill_chunk=16,
                  prefix_cache=True)
    pool_before = np.asarray(jax.tree.leaves(s.engine.cache)[0][1:]).copy()
    s.engine.warm_block_copy(execute=True)  # trash → trash self-copy
    np.testing.assert_array_equal(
        np.asarray(jax.tree.leaves(s.engine.cache)[0][1:]), pool_before
    )
    compiled = s.engine.warm_block_copy(execute=False)
    assert compiled is not None  # the cost-card AOT branch
    serving_registry(s.engine).assert_covers(
        s.engine.compiled_program_names()
    )


# ---------------------------------------------------------------------------
# fleet: disagg identity, affinity LRU, sticky rung, JSONL/report
# ---------------------------------------------------------------------------


def _fleet_trace(n=10, sessions=4):
    from pytorch_distributed_tpu.fleet import generate_trace

    return generate_trace(
        seed=3, duration_s=float(4 * n), base_rate=n / (4.0 * n),
        burst_rate_mult=2.0, burst_every_s=10.0, burst_len_s=2.0,
        sessions=sessions, prompt_median=10, prompt_sigma=0.6,
        prompt_min=4, prompt_max=24, max_new_median=5,
        max_new_sigma=0.4, max_new_min=2, max_new_max=8,
    )


def _replay(router, trace, cfg, prefix_len=24):
    from pytorch_distributed_tpu.fleet import (
        replay_trace,
        shared_prefix_prompt_for,
    )

    replay_trace(
        trace,
        lambda r: router.submit(
            shared_prefix_prompt_for(r, cfg.vocab_size, prefix_len),
            r.max_new, session=r.session,
        ),
        router.step,
        lambda: router.idle,
    )
    return dict(router.results)


@pytest.mark.slow
def test_prefix_fleet_and_disagg_handoff_identity(tmp_path):
    """Shared-prefix chains cross the disaggregated prefill→decode
    handoff intact (export gathers shared blocks, the decode pool gets
    its own exclusive copies) and both the plain and disagg prefix
    fleets stream token-identically to the prefix-off fleet. Also the
    rollup + JSONL end of the satellite: fleet metrics carry the hit
    rate and the ON run's stream validates against the schema
    registry."""
    from pytorch_distributed_tpu.fleet import FleetRouter
    from pytorch_distributed_tpu.telemetry.schema import validate_stream
    from pytorch_distributed_tpu.utils.profiling import MetricsLogger

    cfg, params = setup(max_seq_len=64)
    trace = _fleet_trace()
    kw = dict(n_slots=3, block_len=8, prefill_chunk=16, admit_per_step=4)
    path = tmp_path / "prefix.jsonl"
    mlog = MetricsLogger(str(path))
    on = FleetRouter(cfg, params, n_replicas=2, prefix_cache=True,
                     metrics_log=mlog, **kw)
    got_on = _replay(on, trace, cfg)
    on.log_summary()
    mlog.close()
    off = FleetRouter(cfg, params, n_replicas=2, **kw)
    got_off = _replay(off, trace, cfg)
    assert got_on == got_off
    disagg = FleetRouter(cfg, params, n_replicas=2, disaggregate=True,
                         prefix_cache=True, **kw)
    assert _replay(disagg, trace, cfg) == got_off
    assert disagg.metrics()["handoffs"] > 0
    m = on.metrics()
    assert m["prefix_hits"] > 0 and 0 < m["prefix_hit_rate"] <= 1
    assert m["admitted_prefill_tokens"] < off.metrics()[
        "admitted_prefill_tokens"]
    records = [json.loads(l) for l in path.read_text().splitlines() if l]
    assert not validate_stream(records)
    assert any(r.get("kind") == "prefix" and r.get("covered", 0) > 0
               for r in records)
    # fleet-wide coverage guard stays green with the COW/hit paths live
    on.assert_registry_covers()
    disagg.assert_registry_covers()


def test_prefix_report_section(tmp_path):
    import sys

    sys.path.insert(0, "scripts")
    try:
        import telemetry_report
    finally:
        sys.path.pop(0)

    path = tmp_path / "t.jsonl"
    with open(path, "w") as f:
        for i in range(4):
            f.write(json.dumps({
                "kind": "prefix", "rid": i, "replica_id": 0,
                "prompt_len": 40, "covered": 24 if i else 0,
                "shared_blocks": 3 if i else 0, "cow": i == 3,
                "evicted": 0, "ts": float(i),
            }) + "\n")
    assert telemetry_report.main([str(path), "--require", "prefix"]) == 0
    assert telemetry_report.main([str(path), "--require", "pressure"]) == 2


def test_affinity_lru_cap_regression():
    """The round-17 satellite fix: the router's session-affinity table
    is LRU-bounded — 100k sessions can no longer grow it without
    bound, and recently-routed sessions survive the cap."""
    from pytorch_distributed_tpu.fleet import FleetRouter

    cfg, params = setup(max_seq_len=64)
    router = FleetRouter(cfg, params, n_replicas=2, affinity_cap=4,
                         n_slots=3, block_len=8, prefill_chunk=16)
    prompt = np.arange(1, 9, dtype=np.int32)
    for sess in range(6):
        router.submit(prompt, 2, session=sess)
    router.submit(prompt, 2, session=2)  # touch keeps session 2 recent
    router.submit(prompt, 2, session=6)  # evicts the LRU entry
    router.drain()
    m = router.metrics()
    assert len(router._affinity) <= 4
    assert m["affinity_evictions"] >= 2 and m["affinity_sessions"] <= 4
    assert 2 in router._affinity and 0 not in router._affinity
    with pytest.raises(ValueError, match="affinity_cap"):
        FleetRouter(cfg, params, n_replicas=2, affinity_cap=0,
                    n_slots=3, block_len=8, prefill_chunk=16)


def test_gate_prefix_sticky_rung():
    from pytorch_distributed_tpu.fleet import SLOConfig, SLOGate
    from pytorch_distributed_tpu.fleet.admission import ADMIT, SPILL

    def m(depth, prefix=True, draining=False):
        return {"queue_depth": depth, "occupancy": 0.5,
                "prefix_cache": prefix, "draining": draining}

    gate = SLOGate(SLOConfig(spill_queue_depth=4, shed_queue_depth=64,
                             prefix_sticky_depth=8))
    # hot only by queue depth + prefix resident → stay sticky
    d = gate.route({0: m(5), 1: m(0)}, preferred=0)
    assert d.action == ADMIT and d.replica == 0
    assert d.reason == "prefix-sticky"
    # past the sticky bound → spill as before
    d = gate.route({0: m(9), 1: m(0)}, preferred=0)
    assert d.action == SPILL and d.replica == 1
    # no prefix cache on the replica → the rung does not apply
    d = gate.route({0: m(5, prefix=False), 1: m(0)}, preferred=0)
    assert d.action == SPILL
    # draining is never sticky
    d = gate.route({0: m(5, draining=True), 1: m(0)}, preferred=0)
    assert d.action == SPILL
    # default config: rung off, historical behavior bit-identical
    d = SLOGate(SLOConfig(spill_queue_depth=4)).route(
        {0: m(5), 1: m(0)}, preferred=0
    )
    assert d.action == SPILL
    with pytest.raises(ValueError, match="prefix_sticky_depth"):
        SLOConfig(spill_queue_depth=4, shed_queue_depth=8,
                  prefix_sticky_depth=9)


# ---------------------------------------------------------------------------
# TP=2 (slow tier, like the other TP parity tests)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_prefix_tp2_token_identity():
    """TP=2 CPU mesh: the head-sharded pool shares blocks per shard
    (same ids, each shard's head slice) and the COW program copies
    under shard_map — streams identical to the TP=2 no-sharing
    scheduler."""
    import dataclasses

    from pytorch_distributed_tpu.parallel import make_mesh

    rep = tiny_config(attention="dense", max_seq_len=96, num_heads=4)
    tpcfg = dataclasses.replace(rep, model_axis="model", tp_size=2)
    params = TransformerLM(rep).init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    mesh = make_mesh(jax.devices()[:2], data_parallel=1, seq_parallel=1,
                     model_parallel=2)
    prompts = _shared_prompts(rep, tails=(8, 9))
    prompts.append(prompts[0].copy())  # block-aligned twin: COW under TP
    budgets = [5, 5, 5]
    on = Scheduler(tpcfg, params, n_slots=2, block_len=8,
                   prefill_chunk=16, mesh=mesh, prefix_cache=True)
    off = Scheduler(tpcfg, params, n_slots=2, block_len=8,
                    prefill_chunk=16, mesh=mesh)
    assert list(drive(on, prompts, budgets).values()) == \
        list(drive(off, prompts, budgets).values())
    assert on.metrics()["prefix_hits"] >= 2
    assert on.metrics()["prefix_cow_copies"] >= 1
