"""Cold-start elimination (round 8 tentpole): program registry coverage,
AOT export round-trip, persistent-cache hits, warmup runner ordering,
scheduler cold-request honesty, and the double-fit zero-new-jit-entries
regression the ISSUE's satellite calls for.

The registry's contract is the dual of ``analysis.guards.no_recompile``:
the guard fails when a program compiles that *shouldn't have*; the
registry predicts every program that *will* — and ``assert_covers`` ties
the two together by failing when the live jit caches hold anything the
enumeration missed.
"""

import contextlib
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_tpu.compilecache import (
    CacheHitCounter,
    CoverageError,
    ProgramRegistry,
    ProgramSpec,
    WarmupRunner,
    enable_persistent_cache,
    export_program,
    load_exported,
    run_fingerprint,
    save_exported,
    serving_registry,
)
from pytorch_distributed_tpu.compilecache.aot import (
    _reset_jax_cache_state,
    artifact_path,
)
from pytorch_distributed_tpu.models.transformer import (
    TransformerLM,
    tiny_config,
)
from pytorch_distributed_tpu.serving import PagedEngine, Scheduler
from pytorch_distributed_tpu.utils.profiling import MetricsLogger


def _lm(max_seq_len=96):
    cfg = tiny_config(attention="dense", max_seq_len=max_seq_len)
    params = TransformerLM(cfg).init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return cfg, params


@contextlib.contextmanager
def _persistent_cache(tmp_path):
    """enable_persistent_cache with the global jax config restored after —
    the suite must not keep writing executables into a dead tmp dir."""
    prev_dir = getattr(jax.config, "jax_compilation_cache_dir", None)
    prev_min_t = getattr(
        jax.config, "jax_persistent_cache_min_compile_time_secs", 1.0
    )
    prev_min_b = getattr(
        jax.config, "jax_persistent_cache_min_entry_size_bytes", 0
    )
    try:
        yield enable_persistent_cache(os.fspath(tmp_path))
    finally:
        jax.config.update("jax_compilation_cache_dir", prev_dir)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", prev_min_t
        )
        jax.config.update(
            "jax_persistent_cache_min_entry_size_bytes", prev_min_b
        )
        _reset_jax_cache_state()  # unbind the tmp dir from the singleton


# ---------------------------------------------------------------------------
# fingerprint + registry (pure host logic — fast tier)
# ---------------------------------------------------------------------------


def test_run_fingerprint_stable_and_sensitive():
    a = run_fingerprint(extra=("cfg_a",))
    assert a == run_fingerprint(extra=("cfg_a",))  # deterministic
    assert a != run_fingerprint(extra=("cfg_b",))  # config keys the cache
    assert a != run_fingerprint()  # extras are part of the key
    assert len(a) == 16 and int(a, 16) >= 0  # short stable hex


def test_registry_rejects_duplicates_and_reports_names():
    reg = ProgramRegistry("fp")
    reg.add(ProgramSpec("a", warm=lambda e: None))
    with pytest.raises(ValueError, match="duplicate"):
        reg.add(ProgramSpec("a", warm=lambda e: None))
    reg.add(ProgramSpec("b", warm=lambda e: None, priority=0))
    assert reg.names == ["a", "b"] and len(reg) == 2
    assert reg.predicts("a") and not reg.predicts("c")


def test_coverage_guard_unpredicted_and_over_budget():
    reg = ProgramRegistry()
    reg.add(ProgramSpec("step", warm=lambda e: None, expect_entries=2))
    reg.assert_covers([])  # fewer live programs than predicted is fine
    reg.assert_covers(["step", "step"])  # at budget
    with pytest.raises(CoverageError, match="outside the registry"):
        reg.assert_covers(["step", "rogue"])
    with pytest.raises(CoverageError, match="retraced past"):
        reg.assert_covers(["step"] * 3)


# ---------------------------------------------------------------------------
# serving registry enumeration vs the engine's live bucketing
# ---------------------------------------------------------------------------


def test_serving_registry_enumerates_every_engine_bucket():
    cfg, params = _lm()
    engine = PagedEngine(cfg, params, n_slots=3, block_len=16,
                         prefill_chunk=32)
    reg = serving_registry(engine)
    assert reg.predicts(engine.DECODE_PROGRAM)
    # every bucket bucket_for can produce must be enumerated: job counts
    # 1..n_slots at every admissible chunk start
    class _Job:
        def __init__(self, start):
            self.start = start

    starts = range(0, cfg.max_seq_len - engine.chunk + 1, engine.chunk)
    for k in range(1, engine.n_slots + 1):
        for start in starts:
            k_pad, wp = engine.bucket_for([_Job(start)] * k)
            assert (k_pad, wp) in engine.chunk_buckets()
            assert reg.predicts(engine.chunk_program_name(k_pad, wp))
    # priority: decode + smallest bucket are serve-critical (foreground)
    by_name = {s.name: s for s in reg}
    assert by_name[engine.DECODE_PROGRAM].priority == 0
    smallest = min(engine.chunk_buckets())
    assert by_name[engine.chunk_program_name(*smallest)].priority == 0


def test_serving_coverage_guard_passes_after_traffic():
    cfg, params = _lm()
    s = Scheduler(cfg, params, n_slots=2, block_len=16, prefill_chunk=32)
    reg = serving_registry(s.engine)
    rng = np.random.default_rng(0)
    for n in (6, 20, 40):
        s.submit(rng.integers(1, cfg.vocab_size, size=n).astype(np.int32), 4)
    s.drain()
    assert s.engine.compiled_program_names()  # something really compiled
    reg.assert_covers(s.engine.compiled_program_names())


# ---------------------------------------------------------------------------
# scheduler cold-request honesty + warmup
# ---------------------------------------------------------------------------


def test_scheduler_cold_flag_lands_in_metrics_and_jsonl(tmp_path):
    cfg, params = _lm()
    path = os.fspath(tmp_path / "serve.jsonl")
    with MetricsLogger(path) as mlog:
        s = Scheduler(cfg, params, n_slots=2, block_len=16,
                      prefill_chunk=32, metrics_log=mlog)
        rng = np.random.default_rng(0)
        for _ in range(4):
            s.submit(rng.integers(1, cfg.vocab_size, size=8)
                     .astype(np.int32), 4)
        s.drain()
        m = s.metrics()
    # the first batch compiled its bucket + the decode tick mid-traffic
    assert m["cold_requests"] >= 1
    assert m["compile_s"] > 0  # the stall was attributed to the ledger
    reqs = [json.loads(line) for line in open(path)
            if json.loads(line).get("kind") == "request"]
    assert len(reqs) == 4 and any(r["cold"] for r in reqs)
    # warm-only TTFT excludes exactly the cold requests
    assert m["ttft_warm_count"] == len(reqs) - m["cold_requests"]
    assert m["ttft_count"] == len(reqs)


def test_scheduler_warmup_eliminates_cold_requests(tmp_path):
    cfg, params = _lm()
    path = os.fspath(tmp_path / "serve.jsonl")
    with MetricsLogger(path) as mlog:
        s = Scheduler(cfg, params, n_slots=2, block_len=16,
                      prefill_chunk=32, metrics_log=mlog)
        runner = s.warmup(background=False)
        assert runner.summary()["programs"] == len(serving_registry(s.engine))
        rng = np.random.default_rng(0)
        for _ in range(4):
            s.submit(rng.integers(1, cfg.vocab_size, size=8)
                     .astype(np.int32), 4)
        s.drain()
        m = s.metrics()
    assert m["cold_requests"] == 0
    records = [json.loads(line) for line in open(path)]
    reqs = [r for r in records if r.get("kind") == "request"]
    assert reqs and not any(r["cold"] for r in reqs)
    # one kind="warmup" manifest record per registry program
    warms = [r for r in records if r.get("kind") == "warmup"]
    assert {r["program"] for r in warms} == set(
        serving_registry(s.engine).names
    )
    # warmed = predicted: the guard closes over the whole run
    serving_registry(s.engine).assert_covers(
        s.engine.compiled_program_names()
    )


def test_scheduler_warmup_background_leaves_serve_critical_hot():
    cfg, params = _lm()
    s = Scheduler(cfg, params, n_slots=2, block_len=16, prefill_chunk=32)
    runner = s.warmup(background=True)
    # the foreground portion (decode tick + smallest bucket) is hot
    # before run() returns — the scheduler can start serving immediately
    assert s.engine.has_decode_program
    smallest = min(s.engine.chunk_buckets())
    assert s.engine.has_chunk_program(*smallest)
    runner.wait(timeout=300)
    recs = runner.records
    assert {r["program"] for r in recs} == set(
        serving_registry(s.engine).names
    )
    bg = [r for r in recs if r["background"]]
    assert bg and all(r["priority"] > 0 for r in bg)


# ---------------------------------------------------------------------------
# warmup runner (fake specs — ordering, manifest, ledger split)
# ---------------------------------------------------------------------------


def test_warmup_runner_priority_order_and_summary():
    order = []
    reg = ProgramRegistry("fp123")
    reg.add(ProgramSpec("late", warm=lambda e: order.append(("late", e)),
                        priority=1))
    reg.add(ProgramSpec("first", warm=lambda e: order.append(("first", e)),
                        priority=0))
    runner = WarmupRunner(reg).run(background=False)
    assert [n for n, _ in order] == ["first", "late"]
    assert all(e for _, e in order)  # foreground warms execute inert
    s = runner.summary()
    assert s["programs"] == 2 and s["fingerprint"] == "fp123"
    assert s["cache_hits"] + s["fresh"] == 2


def test_warmup_runner_background_is_aot_only():
    events = []
    reg = ProgramRegistry()
    reg.add(ProgramSpec("fg", warm=lambda e: events.append(("fg", e)),
                        priority=0))
    reg.add(ProgramSpec("bg", warm=lambda e: events.append(("bg", e)),
                        priority=1))
    runner = WarmupRunner(reg).run(background=True)
    runner.wait(timeout=60)
    assert dict(events) == {"fg": True, "bg": False}  # bg never executes
    recs = {r["program"]: r for r in runner.records}
    assert recs["fg"]["background"] is False
    assert recs["bg"]["background"] is True


def test_warmup_runner_ledger_attribution_foreground_only():
    from pytorch_distributed_tpu.telemetry import GoodputLedger

    ledger = GoodputLedger()
    ledger.start()
    reg = ProgramRegistry()
    reg.add(ProgramSpec("fg", warm=lambda e: None, priority=0))
    reg.add(ProgramSpec("bg", warm=lambda e: None, priority=1))
    runner = WarmupRunner(reg, ledger=ledger).run(background=True)
    runner.wait(timeout=60)
    fg = [r for r in runner.records if not r["background"]][0]
    # the foreground compile's wall time is fully classified (compile +
    # trace); background compiles never stall the run, so never book time
    booked = ledger.seconds("compile") + ledger.seconds("trace")
    assert booked == pytest.approx(fg["seconds"], rel=0.5, abs=0.05)


# ---------------------------------------------------------------------------
# AOT artifacts: export round-trip + corruption fall-through
# ---------------------------------------------------------------------------


def test_aot_export_roundtrip_token_identical(tmp_path):
    """Serialize → reload under a fresh fingerprint lookup → greedy decode
    must be token-identical to the in-process JIT path (the satellite's
    round-trip gate)."""
    cfg, params = _lm(max_seq_len=48)
    model = TransformerLM(cfg)
    L = cfg.max_seq_len

    jit_fn = jax.jit(lambda p, toks: model.apply({"params": p}, toks))
    avals = (
        jax.tree.map(lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
                     params),
        jax.ShapeDtypeStruct((1, L), jnp.int32),
    )
    fp = run_fingerprint(extra=(cfg,))
    exported = export_program(jit_fn, *avals)
    path = save_exported(os.fspath(tmp_path), "lm_logits", fp, exported)
    assert os.path.exists(path) and fp in os.path.basename(path)
    # a different environment fingerprint is a MISS, never a wrong program
    assert load_exported(os.fspath(tmp_path), "lm_logits", "0" * 16) is None
    reloaded = load_exported(os.fspath(tmp_path), "lm_logits", fp)
    assert reloaded is not None

    prompt = np.random.default_rng(3).integers(
        1, cfg.vocab_size, size=8
    ).astype(np.int32)

    def greedy(call, steps=10):
        toks = np.zeros((1, L), np.int32)
        toks[0, : len(prompt)] = prompt
        n = len(prompt)
        for _ in range(steps):
            logits = np.asarray(call(params, jnp.asarray(toks)))
            toks[0, n] = int(logits[0, n - 1].argmax())
            n += 1
        return toks[0, len(prompt):n].copy()

    np.testing.assert_array_equal(greedy(jit_fn), greedy(reloaded.call))


def test_load_exported_corruption_falls_through(tmp_path, caplog):
    cache = os.fspath(tmp_path)
    # missing: plain miss, no log noise
    assert load_exported(cache, "ghost", "ab" * 8) is None
    # garbage blob: logged warning + None — never a crash
    path = artifact_path(cache, "bad", "cd" * 8)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "wb") as f:
        f.write(b"definitely not a serialized jax.export program")
    with caplog.at_level("WARNING", logger="pytorch_distributed_tpu"):
        assert load_exported(cache, "bad", "cd" * 8) is None
    assert any("corrupt" in r.message or "stale" in r.message
               for r in caplog.records)
    # truncated real artifact: same fall-through
    cfg, params = _lm(max_seq_len=32)
    jit_fn = jax.jit(
        lambda p, t: TransformerLM(cfg).apply({"params": p}, t)
    )
    avals = (
        jax.tree.map(lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
                     params),
        jax.ShapeDtypeStruct((1, 32), jnp.int32),
    )
    good = save_exported(cache, "torn", "ef" * 8,
                         export_program(jit_fn, *avals))
    blob = open(good, "rb").read()
    with open(good, "wb") as f:
        f.write(blob[: len(blob) // 2])
    assert load_exported(cache, "torn", "ef" * 8) is None


def test_persistent_cache_hit_counter(tmp_path):
    """First compile writes the persistent cache; after clearing the
    in-memory jit caches, recompiling the same program is a disk hit the
    monitoring listener observes — the mechanism CacheHitCounter, the
    warmup manifest's cache_hit flag, and --expect-hits all share."""
    with _persistent_cache(tmp_path / "cc"):
        fn = jax.jit(lambda x: x * 2.0 + 1.0)
        x = jnp.arange(8, dtype=jnp.float32)
        with CacheHitCounter() as cold:
            np.testing.assert_allclose(np.asarray(fn(x)),
                                       np.arange(8) * 2.0 + 1.0)
        jax.clear_caches()
        with CacheHitCounter() as warm:
            fn(x)
        assert warm.hits >= cold.hits + 1


# ---------------------------------------------------------------------------
# trainers: double-fit regression + registry coverage + warmup manifest
# ---------------------------------------------------------------------------


def _resnet_trainer(tmp_path, devices8, **cfg_over):
    from pytorch_distributed_tpu.data import SyntheticImageClassification
    from pytorch_distributed_tpu.models.resnet import BasicBlock, ResNet
    from pytorch_distributed_tpu.parallel import make_mesh
    from pytorch_distributed_tpu.train import Trainer, TrainerConfig

    cfg = TrainerConfig(
        epochs=1, batch_size=2, lr=0.05, save_dir=os.fspath(tmp_path),
        log_every=0, num_workers=0, prefetch=1, **cfg_over,
    )
    model = ResNet(stage_sizes=(1, 1), block_cls=BasicBlock,
                   num_classes=10, num_filters=8)
    return Trainer(
        model,
        SyntheticImageClassification(size=64, image_size=16, num_classes=10),
        SyntheticImageClassification(size=32, image_size=16, num_classes=10,
                                     seed=1),
        cfg, mesh=make_mesh(devices8), input_shape=(1, 16, 16, 3),
    )


def test_trainer_double_fit_zero_new_jit_entries(tmp_path, devices8):
    """Two consecutive fit() runs, same process, identical config: the
    second run must add ZERO jit-cache entries — the same cache-growth
    probe no_recompile watches, extended across whole fit runs."""
    trainer = _resnet_trainer(tmp_path, devices8)
    trainer.fit()
    before = trainer.compiled_program_names()
    assert "train_step" in before and "eval_step" in before
    trainer.assert_registry_covers()  # acceptance: trainers' half
    trainer.fit()
    assert trainer.compiled_program_names() == before
    trainer.assert_registry_covers()


def test_trainer_warmup_populates_cache_and_manifest(tmp_path, devices8):
    with _persistent_cache(tmp_path / "cc") as cache_dir:
        trainer = _resnet_trainer(
            tmp_path / "run", devices8, warmup=True,
            metrics_out=os.fspath(tmp_path / "metrics.jsonl"),
        )
        trainer.fit()
        trainer.assert_registry_covers()
    records = [json.loads(line)
               for line in open(tmp_path / "metrics.jsonl")]
    warms = [r for r in records if r.get("kind") == "warmup"]
    assert {r["program"] for r in warms} == {"train_step", "eval_step"}
    assert all(r["fingerprint"] for r in warms)
    # the AOT lower+compile really wrote executables to disk
    cache_files = [f for _, _, fs in os.walk(cache_dir) for f in fs]
    assert cache_files, "persistent cache dir is empty after warmup"


@pytest.mark.slow
def test_lm_trainer_warmup_registry_coverage(tmp_path, devices8):
    from pytorch_distributed_tpu.data import SyntheticTokens
    from pytorch_distributed_tpu.parallel import make_mesh
    from pytorch_distributed_tpu.train import LMTrainer, LMTrainerConfig

    mesh = make_mesh(devices8[:4], data_parallel=2, seq_parallel=2)
    with _persistent_cache(tmp_path / "cc"):
        cfg = LMTrainerConfig(
            epochs=1, batch_size=2, save_dir=os.fspath(tmp_path / "run"),
            num_workers=0, log_every=0, warmup_steps=0, warmup=True,
            metrics_out=os.fspath(tmp_path / "metrics.jsonl"),
        )
        trainer = LMTrainer(
            tiny_config(attention="ring"),
            SyntheticTokens(16, 32, 128),
            SyntheticTokens(8, 32, 128, seed=1),
            cfg, mesh=mesh,
        )
        trainer.fit()
        trainer.assert_registry_covers()
    records = [json.loads(line)
               for line in open(tmp_path / "metrics.jsonl")]
    warms = [r for r in records if r.get("kind") == "warmup"]
    assert {r["program"] for r in warms} == {"lm_train_step",
                                             "lm_eval_step"}
