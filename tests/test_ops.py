"""Op-level parity tests, several directly against torch CPU."""

import jax
import jax.numpy as jnp
import numpy as np
import torch

from pytorch_distributed_tpu.ops import (
    ClassificationMetrics,
    DynamicLossScaler,
    NoOpLossScaler,
    cross_entropy_loss,
    sgd_with_weight_decay,
    step_lr,
    topk_correct,
)
from pytorch_distributed_tpu.ops.precision import all_finite, bf16_policy


def test_cross_entropy_matches_torch():
    rng = np.random.default_rng(0)
    logits = rng.normal(size=(16, 10)).astype(np.float32)
    labels = rng.integers(0, 10, size=(16,))
    ours = cross_entropy_loss(jnp.asarray(logits), jnp.asarray(labels))
    theirs = torch.nn.functional.cross_entropy(
        torch.tensor(logits), torch.tensor(labels)
    )
    np.testing.assert_allclose(float(ours), float(theirs), rtol=1e-5)


def test_cross_entropy_reductions_and_smoothing():
    rng = np.random.default_rng(1)
    logits = jnp.asarray(rng.normal(size=(8, 5)).astype(np.float32))
    labels = jnp.asarray(rng.integers(0, 5, size=(8,)))
    per = cross_entropy_loss(logits, labels, reduction="none")
    assert per.shape == (8,)
    np.testing.assert_allclose(
        float(cross_entropy_loss(logits, labels, reduction="sum")),
        float(jnp.sum(per)),
        rtol=1e-6,
    )
    smoothed = cross_entropy_loss(logits, labels, label_smoothing=0.1)
    theirs = torch.nn.functional.cross_entropy(
        torch.tensor(np.asarray(logits)),
        torch.tensor(np.asarray(labels, dtype=np.int64)),
        label_smoothing=0.1,
    )
    np.testing.assert_allclose(float(smoothed), float(theirs), rtol=1e-5)


def test_topk_correct_matches_torch_topk():
    # Mirrors the reference's validation math (restnet_ddp.py:58-60).
    rng = np.random.default_rng(2)
    logits = rng.normal(size=(32, 20)).astype(np.float32)
    labels = rng.integers(0, 20, size=(32,))
    ours = topk_correct(jnp.asarray(logits), jnp.asarray(labels), ks=(1, 5))
    t_logits, t_labels = torch.tensor(logits), torch.tensor(labels)
    _, preds = t_logits.topk(5, -1, True, True)
    c1 = torch.eq(preds[:, :1], t_labels.unsqueeze(1)).sum()
    c5 = torch.eq(preds, t_labels.unsqueeze(1)).sum()
    assert int(ours["correct1"]) == int(c1)
    assert int(ours["correct5"]) == int(c5)


def test_metrics_accumulate_and_summarize():
    m = ClassificationMetrics.empty()
    logits = jnp.asarray([[5.0, 0.0], [0.0, 5.0]])
    labels = jnp.asarray([0, 0])
    m = m.merge(ClassificationMetrics.from_step(jnp.asarray(1.0), logits, labels))
    m = m.merge(ClassificationMetrics.from_step(jnp.asarray(3.0), logits, labels))
    s = m.summary(num_batches=2)
    assert s["count"] == 4
    assert s["loss"] == 2.0
    assert s["acc1"] == 50.0
    assert s["acc5"] == 100.0  # 2 classes => top-5 always hits


def test_sgd_matches_torch_exactly():
    """Bit-level parity of the update rule with torch.optim.SGD
    (lr=0.1, momentum=0.9, weight_decay=1e-4 — restnet_ddp.py:122)."""
    rng = np.random.default_rng(3)
    w0 = rng.normal(size=(7, 3)).astype(np.float32)

    tw = torch.tensor(w0.copy(), requires_grad=True)
    topt = torch.optim.SGD([tw], lr=0.1, momentum=0.9, weight_decay=1e-4)

    tx = sgd_with_weight_decay(0.1, momentum=0.9, weight_decay=1e-4)
    params = {"w": jnp.asarray(w0)}
    opt_state = tx.init(params)

    for step in range(5):
        g = rng.normal(size=w0.shape).astype(np.float32)
        topt.zero_grad()
        tw.grad = torch.tensor(g.copy())
        topt.step()
        updates, opt_state = tx.update({"w": jnp.asarray(g)}, opt_state, params)
        params = jax.tree.map(lambda p, u: p + u, params, updates)
        np.testing.assert_allclose(
            np.asarray(params["w"]), tw.detach().numpy(), rtol=1e-5, atol=1e-6
        )


def test_step_lr_schedule():
    # StepLR(step_size=30, gamma=0.1) over epochs (resnet_single_gpu.py:109).
    sched = step_lr(0.1, steps_per_epoch=10, step_size_epochs=30, gamma=0.1)
    np.testing.assert_allclose(float(sched(0)), 0.1, rtol=1e-6)
    np.testing.assert_allclose(float(sched(299)), 0.1, rtol=1e-6)  # epoch 29
    np.testing.assert_allclose(float(sched(300)), 0.01, rtol=1e-6)  # epoch 30
    np.testing.assert_allclose(float(sched(600)), 0.001, rtol=1e-6)  # epoch 60


def test_bf16_policy_casts():
    policy = bf16_policy()
    tree = {"a": jnp.ones((2, 2), jnp.float32), "i": jnp.ones((2,), jnp.int32)}
    cast = policy.cast_to_compute(tree)
    assert cast["a"].dtype == jnp.bfloat16
    assert cast["i"].dtype == jnp.int32  # non-float leaves untouched
    back = policy.cast_to_param(cast)
    assert back["a"].dtype == jnp.float32


def test_dynamic_loss_scaler_backoff_and_growth():
    scaler = DynamicLossScaler.create(init_scale=16.0, growth_interval=2)
    assert float(scaler.scale_loss(jnp.asarray(2.0))) == 32.0
    grads = {"g": jnp.asarray([32.0])}
    np.testing.assert_allclose(np.asarray(scaler.unscale_grads(grads)["g"]), [2.0])
    # non-finite step: halve, skip
    scaler = scaler.update(jnp.asarray(False))
    assert float(scaler.scale) == 8.0
    # two finite steps: double
    scaler = scaler.update(jnp.asarray(True))
    scaler = scaler.update(jnp.asarray(True))
    assert float(scaler.scale) == 16.0


def test_all_finite_and_noop_scaler():
    assert bool(all_finite({"a": jnp.ones(3)}))
    assert not bool(all_finite({"a": jnp.asarray([1.0, np.inf])}))
    noop = NoOpLossScaler.create()
    loss = jnp.asarray(1.5)
    assert float(noop.scale_loss(loss)) == 1.5
    assert noop.update(jnp.asarray(False)) is noop
