"""Scale observatory (round 21): host-resource census + growth sentinel.

Three claims, each with its regression teeth:

1. The telemetry PIPELINE is itself memory-bounded at soak volume —
   MetricsLogger rotation keeps disk at ~2x the cap and ``read_mirror``
   stitches the rotated generation back in order, torn tail and all.
2. Every long-lived container in the serving stack is DECLARED with a
   bound class, the census meta-test fails the build when a new one
   appears undeclared, and the bounds it declares actually hold on a
   real fleet (the round-21 leak fixes — ``_origin`` popped at retire,
   streaming retention, the reject-table cap — each get a regression
   cell here).
3. The growth sentinel's fit is honest in both directions: a noise-free
   linear ramp must flag (the raw-MAD-of-ys formulation masked exactly
   that case), and a constant or noisy-flat series must NOT flag (the
   MAD floors).

The 100k-session soak itself is ``@slow``; a 3k-session cell rides
tier-1 via the same ``measure_soak`` entry ci_check --soak-smoke uses.
"""

import json
import os

import numpy as np
import pytest

from pytorch_distributed_tpu.telemetry import (
    Decl,
    GrowthSentinel,
    NULL_MONITOR,
    ResourceMonitor,
    StructCensus,
    audit_owner,
    fit_growth,
    mad_scale,
    rss_mib,
    undeclared_containers,
)
from pytorch_distributed_tpu.telemetry.flightrec import read_mirror
from pytorch_distributed_tpu.telemetry.latency import LatencySeries
from pytorch_distributed_tpu.telemetry.reqtrace import ReqTracer
from pytorch_distributed_tpu.telemetry.schema import validate_stream
from pytorch_distributed_tpu.utils.profiling import MetricsLogger


# ---------------------------------------------------------------------------
# 1. the pipeline itself: rotation + mirror stitching at volume
# ---------------------------------------------------------------------------

def test_metrics_logger_rotates_and_mirror_stitches(tmp_path):
    """Soak volume through a capped log: every record survives exactly
    one rotation boundary away, in order, with disk bounded."""
    path = str(tmp_path / "m.jsonl")
    n = 3000
    with MetricsLogger(path, max_bytes=32 << 10) as mlog:
        for i in range(n):
            mlog.log(kind="resource", seq=i, rss_mib=100.0 + i * 0.001,
                     rss_source="proc", live=3, cumulative=i)
        rotations = mlog.rotations
    assert rotations >= 2, "cap never tripped — rotation path untested"
    # only two generations on disk, both under ~the cap
    assert os.path.exists(path) and os.path.exists(path + ".1")
    assert not os.path.exists(path + ".2")
    assert os.path.getsize(path) <= (32 << 10) + 4096
    events = read_mirror(path)
    seqs = [e["seq"] for e in events]
    # the mirror keeps the NEWEST window (older generations are gone by
    # design) and what it keeps is contiguous and in write order
    assert seqs == list(range(seqs[0], n))
    assert len(events) >= 2, "mirror lost the rotated generation"


def test_read_mirror_skips_torn_tail_and_reopen_appends(tmp_path):
    path = str(tmp_path / "m.jsonl")
    with MetricsLogger(path) as mlog:
        for i in range(10):
            mlog.log(kind="census", seq=i, ok=True, violations=0,
                     structures={}, worst_ratio=0.0)
    # SIGKILL mid-write leaves a torn final line
    with open(path, "a") as f:
        f.write('{"kind": "census", "seq": 10, "ok": tr')
    events = read_mirror(path)
    assert [e["seq"] for e in events] == list(range(10))
    # a relaunch reopens in append mode: old records stay, new ones land
    with MetricsLogger(path) as mlog:
        mlog.log(kind="census", seq=11, ok=True, violations=0,
                 structures={}, worst_ratio=0.0)
    events = read_mirror(path)
    assert events[-1]["seq"] == 11
    assert events[0]["seq"] == 0


# ---------------------------------------------------------------------------
# 2a. census primitives
# ---------------------------------------------------------------------------

class _Owner:
    """Minimal owner with one of each bound class + a callable cap."""

    def __init__(self):
        self.ring = []            # fixed
        self.per_req = {}         # live
        self.lanes = []           # replicas
        self.log = []             # unbounded (declared as such)
        self.cap = 4

    def census_decls(self):
        return [
            Decl("ring", "fixed", cap=lambda o: o.cap, why="test ring"),
            Decl("per_req", "live", per_live=2, why="2 entries per live"),
            Decl("lanes", "replicas", why="one lane per replica"),
            Decl("log", "unbounded", why="caller-owned; never audited"),
        ]


def test_audit_owner_bound_classes():
    o = _Owner()
    o.ring = list(range(4))
    o.per_req = {i: i for i in range(6)}
    o.lanes = [0, 1]
    o.log = list(range(10_000))
    sizes, viol, undecl = audit_owner("o", o, live=3, replicas=2)
    assert sizes == {"o.ring": 4, "o.per_req": 6, "o.lanes": 2,
                     "o.log": 10_000}
    assert viol == [] and undecl == []
    # fixed: one past the (callable) cap flags
    o.ring.append(99)
    _, viol, _ = audit_owner("o", o, live=3, replicas=2)
    assert [v["name"] for v in viol] == ["o.ring"]
    assert viol[0]["bound"] == 4 and viol[0]["size"] == 5
    o.ring.pop()
    # live: bound scales with live count (2 per live + slack)
    o.per_req = {i: i for i in range(9)}
    _, viol, _ = audit_owner("o", o, live=3, replicas=2, live_slack=2)
    assert [v["name"] for v in viol] == ["o.per_req"]  # 9 > 2*3+2
    _, viol, _ = audit_owner("o", o, live=4, replicas=2, live_slack=2)
    assert viol == []                                  # 9 <= 2*4+2
    # live with live=None: skipped, never a false flag
    _, viol, _ = audit_owner("o", o, live=None, replicas=2)
    assert viol == []
    # replicas: one lane past the replica count flags
    o.lanes = [0, 1, 2]
    _, viol, _ = audit_owner("o", o, live=99, replicas=2)
    assert [v["name"] for v in viol] == ["o.lanes"]
    # unbounded never flags, however big
    o.lanes = [0, 1]
    o.log = list(range(1_000_000))
    _, viol, _ = audit_owner("o", o, live=99, replicas=2)
    assert viol == []


def test_undeclared_container_is_loud():
    o = _Owner()
    o.scratch = {}  # the leak-in-waiting: a container nobody declared
    assert undeclared_containers(o) == ["scratch"]
    _, _, undecl = audit_owner("o", o, live=1, replicas=1)
    assert undecl == ["o.scratch"]
    c = StructCensus()
    c.register("o", o)
    rec = c.sweep(live=1, replicas=1)
    assert rec["ok"] is False and rec["undeclared"] == ["o.scratch"]
    assert c.verdict() == "undeclared:1"


def test_dotted_decl_does_not_cover_direct_attr():
    """Decl("ttft.values") reaches through; it must not silence a
    sibling container literally named ``ttft``."""

    class O:
        def __init__(self):
            self.ttft = []

        def census_decls(self):
            return [Decl("ttft.values", "fixed", cap=8, why="reach-through")]

    assert undeclared_containers(O()) == ["ttft"]


def test_census_sweep_verdict_and_peaks(tmp_path):
    path = str(tmp_path / "c.jsonl")
    o = _Owner()
    with MetricsLogger(path) as mlog:
        c = StructCensus(mlog)
        c.register("o", o)
        o.ring = [1, 2]
        c.sweep(live=1, replicas=1, tick=0)
        o.ring = [1, 2, 3]
        c.sweep(live=1, replicas=1, tick=1)
        o.ring = [1]
        rec = c.sweep(live=1, replicas=1, tick=2)
    assert rec["ok"] is True
    assert c.verdict() == "ok"
    assert c.peak["o.ring"] == 3  # peaks survive the shrink
    assert rec["worst_ratio"] == 0.25 and rec["worst_name"] == "o.ring"
    rows = [json.loads(l) for l in open(path) if l.strip()]
    assert [r["kind"] for r in rows] == ["census"] * 3
    assert validate_stream(rows) == [], validate_stream(rows)[:3]


# ---------------------------------------------------------------------------
# 2b. the meta-test: every swept owner in a REAL fleet is fully declared
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def tiny_fleet():
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_tpu.fleet import FleetRouter
    from pytorch_distributed_tpu.models.transformer import (
        TransformerLM,
        tiny_config,
    )

    cfg = tiny_config(attention="dense", max_seq_len=96)
    params = TransformerLM(cfg).init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return cfg, params


def _serve(cfg, params, **kw):
    from pytorch_distributed_tpu.fleet import FleetRouter

    rng = np.random.default_rng(0)
    router = FleetRouter(cfg, params, n_replicas=2, n_slots=3,
                         block_len=8, prefill_chunk=8, **kw)
    rids = [router.submit(
        rng.integers(1, cfg.vocab_size, (9 + i,)).astype(np.int32), 5)
        for i in range(4)]
    out = router.drain(max_steps=4000)
    return router, rids, out


def test_census_meta_no_undeclared_containers(tiny_fleet):
    """THE tripwire: add a dict/list/set/deque to any swept class
    without a Decl and this fails, naming it. That is the point."""
    cfg, params = tiny_fleet
    for retain in (True, False):
        router, _, _ = _serve(cfg, params, retain_results=retain)
        owners = router.census_owners()
        assert owners, "router exposed no census owners"
        # round 22: the HTTP front door sits on the same fleet and keeps
        # its own long-lived tables (streams, ingress/cancel queues,
        # wire-latency rings) — sweep its owners too. Unstarted: no
        # threads, the container inventory is identical.
        from pytorch_distributed_tpu.gateway import Gateway

        owners = owners + Gateway(router).census_owners()
        for name, obj in owners:
            undecl = undeclared_containers(obj)
            assert undecl == [], (
                f"{name} ({type(obj).__name__}) grew undeclared "
                f"container(s) {undecl} — add a Decl with a bound class "
                f"(fixed/live/replicas/unbounded) and a why")


def test_census_sweep_clean_on_live_fleet(tiny_fleet):
    cfg, params = tiny_fleet
    router, rids, out = _serve(cfg, params, retain_results=False)
    census = StructCensus()
    census.register_many(router.census_owners())
    rec = census.sweep(live=router.live_requests(), replicas=2,
                       live_slack=12)
    assert rec["ok"] is True, rec["violation_details"] or rec["undeclared"]
    assert census.verdict() == "ok"
    assert all(len(out.get(r, [])) == 0 for r in rids)  # streaming drops


# ---------------------------------------------------------------------------
# 2c. leak regressions (the fixes the census caught, pinned forever)
# ---------------------------------------------------------------------------

def test_reqtracer_roots_purged_on_close():
    tr = ReqTracer(enabled=True)
    for rid in range(50):
        root = tr.open_root(rid)
        s = tr.begin(rid, "decode", parent=root)
        tr.end(s)
        tr.end(root)
    assert tr.open_traces() == []
    assert tr.open_spans() == []
    # per-rid root registry must not retain closed traces (O(live), not
    # O(sessions ever)) — this is what the ``live`` census bound audits
    sizes, viol, _ = audit_owner("reqtrace", tr, live=0, live_slack=4)
    assert viol == [], viol
    # end() after the root is gone is a no-op, not a resurrection
    tr.end(root)
    assert tr.open_traces() == []


def test_latency_series_window_bounded():
    s = LatencySeries("ttft", window=64)
    for i in range(1000):
        s.observe(i * 1e-3)
    assert len(s) == 1000                      # cumulative count intact
    assert len(s.window_values()) == 64        # percentile window capped
    sizes, viol, _ = audit_owner("lat", s, live=0)
    assert viol == [], viol
    assert all(v <= 2 * 64 for v in sizes.values()), sizes
    sm = s.summary("ttft")
    assert sm["ttft_count"] == 1000
    assert sm["ttft_max_s"] == pytest.approx(0.999)


def test_router_streaming_retention(tiny_fleet):
    """retain_results=False: per-request state is GONE after retire;
    retain_results=True keeps the full transcript (the default)."""
    cfg, params = tiny_fleet
    router, rids, out = _serve(cfg, params, retain_results=True)
    assert all(len(out[r]) == 5 for r in rids)
    assert router._origin == {}  # popped at retire in EVERY mode
    assert router.metrics()["results_dropped"] == 0

    router, rids, out = _serve(cfg, params, retain_results=False)
    assert out == {} or all(len(v) == 0 for v in out.values())
    assert router.results == {}
    assert router._origin == {}
    m = router.metrics()
    assert m["results_dropped"] == len(rids)
    assert m["completed"] == len(rids)  # counters outlive the payloads


def test_router_reject_table_capped(tiny_fleet):
    cfg, params = tiny_fleet
    from pytorch_distributed_tpu.fleet import FleetRouter

    router = FleetRouter(cfg, params, n_replicas=1, n_slots=3,
                         block_len=8, prefill_chunk=8,
                         retain_results=False)
    cap = FleetRouter._REJECT_CAP
    prompt = np.arange(1, 9, dtype=np.int32)
    n = cap + 50
    for _ in range(n):
        router.submit(prompt, 4, deadline_s=-0.01)  # sheds at admission
    assert len(router.rejected) <= cap
    assert router.metrics()["shed"] == n  # the counter stays exact


# ---------------------------------------------------------------------------
# 3. growth sentinel: flags real growth, holds its tongue on noise
# ---------------------------------------------------------------------------

def test_fit_growth_linear_ramp_flags():
    """Noise-free linear growth MUST flag. The naive scale =
    MAD(ys) formulation sees the trend itself as spread and stays
    silent — this is the regression test for the residual-based fix."""
    xs = list(range(0, 3200, 100))
    ys = [100.0 + 0.05 * x for x in xs]
    fit = fit_growth(xs, ys, abs_floor=1.0)
    assert fit["verdict"] == "linear", fit
    assert fit["slope"] == pytest.approx(0.05, rel=1e-6)


def test_fit_growth_flat_and_noise_floors():
    xs = list(range(0, 3200, 100))
    # bit-identical constant: MAD is 0, the floors keep scale > 0
    fit = fit_growth(xs, [137.0] * len(xs), abs_floor=1.0)
    assert fit["verdict"] == "flat", fit
    # trendless noise around a level: stays flat
    rng = np.random.default_rng(7)
    ys = [200.0 + float(rng.normal(0, 2.0)) for _ in xs]
    fit = fit_growth(xs, ys, abs_floor=1.0)
    assert fit["verdict"] == "flat", fit
    # the same noise ON a ramp still flags
    ys = [200.0 + 0.05 * x + float(rng.normal(0, 2.0)) for x in xs]
    fit = fit_growth(xs, ys, abs_floor=1.0)
    assert fit["verdict"] in ("linear", "superlinear"), fit


def test_fit_growth_superlinear_and_insufficient():
    xs = list(range(0, 3200, 100))
    fit = fit_growth(xs, [100.0 + 1e-4 * x * x for x in xs], abs_floor=1.0)
    assert fit["verdict"] == "superlinear", fit
    assert fit_growth([1, 2], [1.0, 2.0])["verdict"] == "insufficient"


def test_mad_scale_floors():
    assert mad_scale([5.0] * 20, rel_floor=0.05) == pytest.approx(0.25)
    assert mad_scale([0.0] * 20, abs_floor=1e-9) == pytest.approx(1e-9)


def test_growth_sentinel_flags_and_is_bounded():
    s = GrowthSentinel(window=256, threshold=4.0, abs_floor=0.5)
    for i in range(64):
        x = float(i * 100)
        s.observe_sizes(x, {"leaky": int(10 + i * 5), "steady": 32})
    rep = s.report()
    assert rep["size:leaky"]["verdict"] in ("linear", "superlinear")
    assert rep["size:steady"]["verdict"] == "flat"
    assert s.flags() == ["size:leaky"]
    # the sentinel's own rings are census-declared and bounded
    assert undeclared_containers(s) == []
    _, viol, _ = audit_owner("sentinel", s)
    assert viol == []


# ---------------------------------------------------------------------------
# 4. resource monitor: cadence, schema, tracemalloc, null object
# ---------------------------------------------------------------------------

def test_rss_mib_reads_something():
    val, source = rss_mib()
    assert val > 1.0
    assert source in ("proc", "rusage")


def test_resource_monitor_cadence_and_schema(tmp_path):
    path = str(tmp_path / "r.jsonl")
    with MetricsLogger(path) as mlog:
        mon = ResourceMonitor(mlog, every_ticks=10, gc_objects=True,
                              tracemalloc_every=2, top_sites=3)
        for t in range(95):
            mon.tick(live=t % 7, cumulative=t, wall_s=0.001)
        mon.close()
    rows = [json.loads(l) for l in open(path) if l.strip()]
    assert len(rows) == 9  # ticks 10, 20, ... 90
    assert validate_stream(rows) == [], validate_stream(rows)[:3]
    for r in rows:
        assert r["kind"] == "resource"
        assert r["rss_mib"] > 1.0 and r["rss_source"] in ("proc", "rusage")
        assert r["cumulative"] % 10 == 9  # sampled ON the cadence tick
        assert "gc_objects" in r
    # tracemalloc armed lazily, then every 2nd sample carries top sites
    tm = [r for r in rows if "tracemalloc_top" in r]
    assert len(tm) >= 3
    assert all(len(r["tracemalloc_top"]) <= 3 for r in tm)
    # series come back as (xs, ys) ready for fit_growth
    xs, ys = mon.rss_series()
    assert len(xs) == len(ys) == 9
    assert fit_growth(xs, ys, rel_floor=0.005, abs_floor=1.0)[
        "verdict"] in ("flat", "linear", "insufficient")
    # the monitor audits itself: history ring declared and bounded
    assert undeclared_containers(mon) == []


def test_resource_monitor_disabled_and_null():
    mon = ResourceMonitor(None, every_ticks=1, enabled=False)
    for t in range(5):
        mon.tick(live=0, cumulative=t, wall_s=0.0)
    assert mon.rss_series() == ([], [])
    for t in range(5):  # the shared no-op object: safe to hammer
        NULL_MONITOR.tick(live=0, cumulative=t, wall_s=0.0)
    NULL_MONITOR.close()


# ---------------------------------------------------------------------------
# 5. the soak harness end-to-end (tier-1 miniature + @slow heavy cell)
# ---------------------------------------------------------------------------

def _run_soak(tmp_path, requests, **kw):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench_serving",
        os.path.join(os.path.dirname(__file__), os.pardir, "scripts",
                     "bench_serving.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    return bench.measure_soak(
        requests=requests, out_path=str(tmp_path / "soak.jsonl"), **kw)


def test_soak_miniature(tmp_path):
    """The --soak path itself: stream sessions through the 2-replica
    fleet with the observatory armed; census must close ok and the
    telemetry must round-trip the rotated mirror."""
    # 150 sessions, not 300: this is the slowest fast-tier test and the
    # tier sits a few seconds under its 870 s cap — the 20k @slow cell
    # carries the volume; the 16 KiB cap keeps rotation exercised.
    row = _run_soak(tmp_path, 150, log_max_bytes=16 << 10)
    assert row["serving_soak_sessions"] == 150
    assert row["serving_soak_completed"] + row["serving_soak_shed"] == 150
    assert row["serving_soak_census_verdict"] == "ok"
    assert row["serving_soak_census_undeclared"] == 0
    assert row["serving_soak_undeclared_at_start"] == 0
    assert row["serving_soak_results_dropped"] == \
        row["serving_soak_completed"]
    assert row["serving_soak_rss_mib_final"] > 1.0
    events = read_mirror(str(tmp_path / "soak.jsonl"))
    kinds = {e.get("kind") for e in events}
    assert "resource" in kinds and "census" in kinds
    assert validate_stream(events) == [], validate_stream(events)[:3]


@pytest.mark.slow
def test_soak_heavy(tmp_path):
    """~20k sessions: enough x-range for the RSS fit to mean something
    off the shared-CPU noise floor. The 100k run is the BENCH row."""
    row = _run_soak(tmp_path, 20_000)
    assert row["serving_soak_census_verdict"] == "ok"
    assert row["serving_soak_census_violations"] == 0
    assert row["serving_soak_census_undeclared"] == 0
    assert row["serving_soak_rss_verdict"] in ("flat", "linear"), row
    assert row["serving_soak_rss_slope_mib_per_10k"] < 20.0, row
    assert row["serving_soak_size_flags"] == "none", row
