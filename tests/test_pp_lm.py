"""Pipeline-parallel transformer TRAINING (VERDICT r1 missing #5): real
Block stages through gpipe match the sequential reference — loss and
parameter trajectories — on a (data x model) mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from pytorch_distributed_tpu.models.transformer import tiny_config
from pytorch_distributed_tpu.ops.optim import sgd_with_weight_decay
from pytorch_distributed_tpu.parallel import make_mesh
from pytorch_distributed_tpu.train.lm import shift_labels
from pytorch_distributed_tpu.train.pp import (
    create_pp_lm_state,
    make_pp_lm_train_step,
    make_pp_reference_step,
    shard_pp_state,
)

N_STAGES = 4


def cfg4():
    return tiny_config(num_layers=4)  # 1 block per stage on 4 stages


def batch_np(seed=0, b=4, l=32):
    rng = np.random.default_rng(seed)
    tokens = rng.integers(1, 128, (b, l)).astype(np.int32)
    labels, weights = shift_labels(tokens)
    return {"tokens": tokens, "labels": labels, "weights": weights}


def test_pp_lm_matches_sequential(devices8):
    cfg = cfg4()
    tx = sgd_with_weight_decay(0.1, momentum=0.9)
    mesh = make_mesh(devices8, data_parallel=2, seq_parallel=1,
                     model_parallel=N_STAGES)

    # two independent (deterministically identical) states: the pipelined
    # step donates its input, and device_put may alias the source buffers
    state0 = create_pp_lm_state(cfg, N_STAGES, tx, jax.random.key(0),
                                init_len=32)
    state_ref = create_pp_lm_state(cfg, N_STAGES, tx, jax.random.key(0),
                                   init_len=32)

    state_pp, specs = shard_pp_state(mesh, state0)
    step_pp = make_pp_lm_train_step(mesh, cfg, specs, n_microbatches=2)
    step_ref = make_pp_reference_step(cfg, N_STAGES, tx)

    sh = NamedSharding(mesh, P("data"))
    losses_pp, losses_ref = [], []
    for i in range(4):
        b = batch_np(seed=i)
        batch_pp = {k: jax.device_put(v, sh) for k, v in b.items()}
        state_pp, m_pp = step_pp(state_pp, batch_pp)
        state_ref, m_ref = step_ref(state_ref, b)
        losses_pp.append(float(m_pp["loss"]))
        losses_ref.append(float(m_ref["loss"]))
    np.testing.assert_allclose(losses_pp, losses_ref, rtol=2e-5)

    from conftest import assert_trees_equal

    assert_trees_equal(state_pp.params, state_ref.params, rtol=5e-4, atol=1e-6)


def test_pp_stage_params_are_sharded(devices8):
    cfg = cfg4()
    tx = sgd_with_weight_decay(0.1)
    mesh = make_mesh(devices8, data_parallel=2, model_parallel=N_STAGES)
    state0 = create_pp_lm_state(cfg, N_STAGES, tx, jax.random.key(0),
                                init_len=32)
    state, specs = shard_pp_state(mesh, state0)
    leaf = jax.tree.leaves(state.params["stages"])[0]
    assert leaf.shape[0] == N_STAGES
    assert {s.data.shape[0] for s in leaf.addressable_shards} == {1}
    # momentum for stage params shards the same way
    mom = [m for m in jax.tree.leaves(state.opt_state)
           if isinstance(m, jax.Array) and m.ndim == leaf.ndim
           and m.shape == leaf.shape]
    assert mom and all(
        {s.data.shape[0] for s in m.addressable_shards} == {1} for m in mom
    )


def test_pp_validations(devices8):
    tx = sgd_with_weight_decay(0.1)
    with pytest.raises(ValueError, match="divisible"):
        create_pp_lm_state(tiny_config(num_layers=3), 4, tx, jax.random.key(0))
    with pytest.raises(NotImplementedError, match="dropout"):
        create_pp_lm_state(tiny_config(num_layers=4, dropout=0.1), 4, tx,
                           jax.random.key(0))
    # TP's model-axis collectives would psum across STAGES under PP
    with pytest.raises(ValueError, match="STAGE axis"):
        create_pp_lm_state(
            tiny_config(num_layers=4, model_axis="model", tp_size=2), 4, tx,
            jax.random.key(0),
        )
    with pytest.raises(NotImplementedError, match="MoE"):
        create_pp_lm_state(tiny_config(num_layers=4, n_experts=4), 4, tx,
                           jax.random.key(0))
    mesh = make_mesh(devices8, data_parallel=4, model_parallel=2)
    state = create_pp_lm_state(cfg4(), 4, tx, jax.random.key(0), init_len=16)
    with pytest.raises(ValueError, match="stages"):
        shard_pp_state(mesh, state)  # 4 stages on a model axis of 2
