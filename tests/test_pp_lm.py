"""Pipeline-parallel transformer TRAINING (VERDICT r1 missing #5): real
Block stages through gpipe match the sequential reference — loss and
parameter trajectories — on a (data x model) mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow
from jax.sharding import NamedSharding, PartitionSpec as P

from pytorch_distributed_tpu.models.transformer import tiny_config
from pytorch_distributed_tpu.ops.optim import sgd_with_weight_decay
from pytorch_distributed_tpu.parallel import make_mesh
from pytorch_distributed_tpu.train.lm import shift_labels
from pytorch_distributed_tpu.train.pp import (
    create_pp_lm_state,
    make_pp_lm_train_step,
    make_pp_reference_step,
    shard_pp_state,
)

N_STAGES = 4


def cfg4():
    return tiny_config(num_layers=4)  # 1 block per stage on 4 stages


def batch_np(seed=0, b=4, l=32):
    rng = np.random.default_rng(seed)
    tokens = rng.integers(1, 128, (b, l)).astype(np.int32)
    labels, weights = shift_labels(tokens)
    return {"tokens": tokens, "labels": labels, "weights": weights}


def test_pp_lm_matches_sequential(devices8):
    cfg = cfg4()
    tx = sgd_with_weight_decay(0.1, momentum=0.9)
    mesh = make_mesh(devices8, data_parallel=2, seq_parallel=1,
                     model_parallel=N_STAGES)

    # two independent (deterministically identical) states: the pipelined
    # step donates its input, and device_put may alias the source buffers
    state0 = create_pp_lm_state(cfg, N_STAGES, tx, jax.random.key(0),
                                init_len=32)
    state_ref = create_pp_lm_state(cfg, N_STAGES, tx, jax.random.key(0),
                                   init_len=32)

    state_pp, specs = shard_pp_state(mesh, state0)
    step_pp = make_pp_lm_train_step(mesh, cfg, specs, n_microbatches=2)
    step_ref = make_pp_reference_step(cfg, N_STAGES, tx)

    sh = NamedSharding(mesh, P("data"))
    losses_pp, losses_ref = [], []
    for i in range(4):
        b = batch_np(seed=i)
        batch_pp = {k: jax.device_put(v, sh) for k, v in b.items()}
        state_pp, m_pp = step_pp(state_pp, batch_pp)
        state_ref, m_ref = step_ref(state_ref, b)
        losses_pp.append(float(m_pp["loss"]))
        losses_ref.append(float(m_ref["loss"]))
    np.testing.assert_allclose(losses_pp, losses_ref, rtol=2e-5)

    from conftest import assert_trees_equal

    assert_trees_equal(state_pp.params, state_ref.params, rtol=5e-4, atol=1e-6)


def test_pp_stage_params_are_sharded(devices8):
    cfg = cfg4()
    tx = sgd_with_weight_decay(0.1)
    mesh = make_mesh(devices8, data_parallel=2, model_parallel=N_STAGES)
    state0 = create_pp_lm_state(cfg, N_STAGES, tx, jax.random.key(0),
                                init_len=32)
    state, specs = shard_pp_state(mesh, state0)
    leaf = jax.tree.leaves(state.params["stages"])[0]
    assert leaf.shape[0] == N_STAGES
    assert {s.data.shape[0] for s in leaf.addressable_shards} == {1}
    # momentum for stage params shards the same way
    mom = [m for m in jax.tree.leaves(state.opt_state)
           if isinstance(m, jax.Array) and m.ndim == leaf.ndim
           and m.shape == leaf.shape]
    assert mom and all(
        {s.data.shape[0] for s in m.addressable_shards} == {1} for m in mom
    )


def test_pp_validations(devices8):
    tx = sgd_with_weight_decay(0.1)
    with pytest.raises(ValueError, match="divisible"):
        create_pp_lm_state(tiny_config(num_layers=3), 4, tx, jax.random.key(0))
    # expert PARALLELISM under PP is supported since r4: state creation
    # accepts an EP config (the step validates mesh fit — see
    # test_pp_ep_validations)
    create_pp_lm_state(
        tiny_config(num_layers=4, n_experts=4, moe_every=1,
                    expert_axis="data", ep_size=2),
        4, tx, jax.random.key(0), init_len=16,
    )
    # a TP config sharing the stage axis would psum across stages
    mesh2 = make_mesh(devices8, data_parallel=4, model_parallel=2)
    cfg_tp = tiny_config(num_layers=4, model_axis="model", tp_size=2)
    state2 = create_pp_lm_state(cfg_tp, 2, tx, jax.random.key(0), init_len=16)
    with pytest.raises(ValueError, match="distinct"):
        shard_pp_state(mesh2, state2, axis="model", config=cfg_tp)
    mesh = make_mesh(devices8, data_parallel=4, model_parallel=2)
    state = create_pp_lm_state(cfg4(), 4, tx, jax.random.key(0), init_len=16)
    with pytest.raises(ValueError, match="stages"):
        shard_pp_state(mesh, state)  # 4 stages on a model axis of 2


def test_pp_dropout_matches_reference(devices8):
    """Dropout under PP: the shared pp_dropout_key derivation makes the
    pipelined run reproduce the sequential reference's masks exactly —
    loss trajectories match to fp reassociation."""
    cfg = tiny_config(num_layers=4, dropout=0.2)
    tx = sgd_with_weight_decay(0.1, momentum=0.9)
    mesh = make_mesh(devices8[:4], data_parallel=1, seq_parallel=1,
                     model_parallel=N_STAGES)
    state0 = create_pp_lm_state(cfg, N_STAGES, tx, jax.random.key(0),
                                init_len=32)
    state_ref = create_pp_lm_state(cfg, N_STAGES, tx, jax.random.key(0),
                                   init_len=32)
    state_pp, specs = shard_pp_state(mesh, state0)
    step_pp = make_pp_lm_train_step(mesh, cfg, specs, n_microbatches=2,
                                    dropout_seed=7)
    step_ref = make_pp_reference_step(cfg, N_STAGES, tx, n_microbatches=2,
                                      dropout_seed=7)
    for i in range(3):
        b = batch_np(seed=i)
        bp = {k: jax.device_put(v, NamedSharding(mesh, P("data")))
              for k, v in b.items()}
        state_pp, m_pp = step_pp(state_pp, bp)
        state_ref, m_ref = step_ref(state_ref, b)
        np.testing.assert_allclose(float(m_pp["loss"]), float(m_ref["loss"]),
                                   rtol=2e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(jax.device_get(a)), np.asarray(b), rtol=2e-4,
            atol=2e-5,
        ),
        jax.device_get(state_pp.params), jax.device_get(state_ref.params),
    )


def test_pp_dropout_resume_bit_parity(devices8):
    """Suspend/resume under dropout-PP: keys derive from (seed, step), so
    a restored state continues with the exact masks of an uninterrupted
    run — losses match bitwise."""
    cfg = tiny_config(num_layers=4, dropout=0.2)
    tx = sgd_with_weight_decay(0.1, momentum=0.9)
    mesh = make_mesh(devices8[:4], data_parallel=1, seq_parallel=1,
                     model_parallel=N_STAGES)
    sh = NamedSharding(mesh, P("data"))

    def run(n_steps, state):
        step = make_pp_lm_train_step(mesh, cfg, specs, n_microbatches=2,
                                     dropout_seed=3)
        losses = []
        for i in range(n_steps[0], n_steps[1]):
            b = {k: jax.device_put(v, sh)
                 for k, v in batch_np(seed=i).items()}
            state, m = step(state, b)
            losses.append(float(m["loss"]))
        return state, losses

    state0 = create_pp_lm_state(cfg, N_STAGES, tx, jax.random.key(1),
                                init_len=32)
    state_a, specs = shard_pp_state(mesh, state0)
    state_a, losses_full = run((0, 4), state_a)

    state_b, specs = shard_pp_state(
        mesh, create_pp_lm_state(cfg, N_STAGES, tx, jax.random.key(1),
                                 init_len=32))
    state_b, l01 = run((0, 2), state_b)
    # suspend: round-trip the whole state through host memory, then resume
    host = jax.device_get(state_b)
    from pytorch_distributed_tpu.parallel.mesh import specs_to_shardings

    state_c = jax.device_put(host, specs_to_shardings(mesh, specs))
    state_c, l23 = run((2, 4), state_c)
    assert l01 + l23 == losses_full


def test_pp_tp_matches_sequential(devices8):
    """TP-within-PP: a (data=2, stage=2, model=2) mesh runs Megatron
    collectives inside each stage while activations ride the stage ring;
    the trajectory matches the sequential (TP-free) reference."""
    cfg = tiny_config(num_layers=4, model_axis="model", tp_size=2)
    import dataclasses

    cfg_ref = dataclasses.replace(cfg, model_axis=None, tp_size=1)
    tx = sgd_with_weight_decay(0.1, momentum=0.9)
    mesh = make_mesh(devices8, data_parallel=2, seq_parallel=2,
                     model_parallel=2,
                     axis_names=("data", "stage", "model"))
    n_stages = 2

    state0 = create_pp_lm_state(cfg, n_stages, tx, jax.random.key(0),
                                init_len=32)
    state_ref = create_pp_lm_state(cfg_ref, n_stages, tx, jax.random.key(0),
                                   init_len=32)
    state_pp, specs = shard_pp_state(mesh, state0, axis="stage", config=cfg)
    step_pp = make_pp_lm_train_step(mesh, cfg, specs, n_microbatches=2,
                                    axis="stage")
    step_ref = make_pp_reference_step(cfg_ref, n_stages, tx,
                                      n_microbatches=2)
    sh = NamedSharding(mesh, P("data"))
    for i in range(3):
        b = batch_np(seed=10 + i)
        state_pp, m_pp = step_pp(
            state_pp, {k: jax.device_put(v, sh) for k, v in b.items()}
        )
        state_ref, m_ref = step_ref(state_ref, b)
        np.testing.assert_allclose(float(m_pp["loss"]), float(m_ref["loss"]),
                                   rtol=1e-4)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(jax.device_get(a)), np.asarray(b), rtol=2e-3,
            atol=2e-4,
        ),
        jax.device_get(state_pp.params), jax.device_get(state_ref.params),
    )


def test_pp_moe_matches_reference(devices8):
    """MoE blocks inside stages (replicated experts, aux losses masked to
    real pipeline ticks) match the microbatched sequential reference."""
    cfg = tiny_config(num_layers=4, n_experts=2, moe_every=1,
                      moe_aux_weight=0.02)
    tx = sgd_with_weight_decay(0.1, momentum=0.9)
    mesh = make_mesh(devices8[:4], data_parallel=1, seq_parallel=1,
                     model_parallel=N_STAGES)
    state0 = create_pp_lm_state(cfg, N_STAGES, tx, jax.random.key(2),
                                init_len=32)
    state_ref = create_pp_lm_state(cfg, N_STAGES, tx, jax.random.key(2),
                                   init_len=32)
    state_pp, specs = shard_pp_state(mesh, state0)
    step_pp = make_pp_lm_train_step(mesh, cfg, specs, n_microbatches=2)
    step_ref = make_pp_reference_step(cfg, N_STAGES, tx, n_microbatches=2)
    sh = NamedSharding(mesh, P("data"))
    for i in range(3):
        b = batch_np(seed=20 + i)
        state_pp, m_pp = step_pp(
            state_pp, {k: jax.device_put(v, sh) for k, v in b.items()}
        )
        state_ref, m_ref = step_ref(state_ref, b)
        np.testing.assert_allclose(float(m_pp["loss"]), float(m_ref["loss"]),
                                   rtol=2e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(jax.device_get(a)), np.asarray(b), rtol=2e-4,
            atol=2e-5,
        ),
        jax.device_get(state_pp.params), jax.device_get(state_ref.params),
    )


def test_pp_ep_matches_reference(devices8):
    """EP-under-PP (VERDICT r3 #4, the last composability cell): experts
    sharded over the data axis inside pipeline stages — the all_to_all
    dispatch runs inside every gpipe tick — match the sequential
    replicated-expert reference. Capacity is oversized and the aux weight
    zeroed so routing is identical across layouts (the same isolation
    tests/test_moe.py uses for EP-vs-single-device parity)."""
    import dataclasses

    cfg = tiny_config(num_layers=4, n_experts=2, moe_every=1,
                      capacity_factor=float(2 * 8), moe_aux_weight=0.0,
                      expert_axis="data", ep_size=2)
    cfg_ref = dataclasses.replace(cfg, expert_axis=None, ep_size=1)
    tx = sgd_with_weight_decay(0.1, momentum=0.9)
    mesh = make_mesh(devices8, data_parallel=2, seq_parallel=1,
                     model_parallel=N_STAGES)
    state0 = create_pp_lm_state(cfg, N_STAGES, tx, jax.random.key(3),
                                init_len=32)
    state_ref = create_pp_lm_state(cfg_ref, N_STAGES, tx, jax.random.key(3),
                                   init_len=32)
    state_pp, specs = shard_pp_state(mesh, state0, config=cfg)
    # expert weights really shard: stage stack on 'model', experts on 'data'
    w_up_spec = specs.params["stages"]["layer0"]["moe"]["w_up"]
    assert w_up_spec == P("model", "data", None, None), w_up_spec
    w_up = state_pp.params["stages"]["layer0"]["moe"]["w_up"]
    assert {s.data.shape for s in w_up.addressable_shards} == {
        (1, 1) + w_up.shape[2:]
    }
    step_pp = make_pp_lm_train_step(mesh, cfg, specs, n_microbatches=2)
    step_ref = make_pp_reference_step(cfg_ref, N_STAGES, tx, n_microbatches=2)
    sh = NamedSharding(mesh, P("data"))
    for i in range(3):
        b = batch_np(seed=30 + i)
        state_pp, m_pp = step_pp(
            state_pp, {k: jax.device_put(v, sh) for k, v in b.items()}
        )
        state_ref, m_ref = step_ref(state_ref, b)
        np.testing.assert_allclose(float(m_pp["loss"]), float(m_ref["loss"]),
                                   rtol=1e-4)
    flat_ref = {str(p): v for p, v in
                jax.tree_util.tree_leaves_with_path(
                    jax.device_get(state_ref.params))}
    for path, leaf in jax.tree_util.tree_leaves_with_path(
            jax.device_get(state_pp.params)):
        np.testing.assert_allclose(
            np.asarray(leaf), np.asarray(flat_ref[str(path)]),
            rtol=5e-4, atol=5e-5, err_msg=str(path),
        )


def test_pp_ep_validations(devices8):
    cfg = tiny_config(num_layers=4, n_experts=2, moe_every=1,
                      expert_axis="seq", ep_size=2)
    tx = sgd_with_weight_decay(0.1)
    mesh = make_mesh(devices8, data_parallel=2, seq_parallel=1,
                     model_parallel=N_STAGES)
    state = create_pp_lm_state(cfg, N_STAGES, tx, jax.random.key(0),
                               init_len=32)
    _, specs = shard_pp_state(mesh, state)
    with pytest.raises(ValueError, match="expert_axis must be the PP data"):
        make_pp_lm_train_step(mesh, cfg, specs)
    cfg_bad = tiny_config(num_layers=4, n_experts=4, moe_every=1,
                          expert_axis="data", ep_size=4)
    with pytest.raises(ValueError, match="ep_size 4 must equal"):
        make_pp_lm_train_step(mesh, cfg_bad, specs)


def test_pp_tp_ep_matches_reference(devices8):
    """The full composed cell — TP inside experts, EP over data, stages
    over the stage axis — against the sequential dense-placement
    reference. Covers the combined-rules spec path (w_up spec names
    stage, data, AND model axes) with real parity, not just a finite-loss
    smoke."""
    import dataclasses

    cfg = tiny_config(num_layers=4, n_experts=2, moe_every=1,
                      capacity_factor=float(2 * 8), moe_aux_weight=0.0,
                      expert_axis="data", ep_size=2,
                      model_axis="model", tp_size=2)
    cfg_ref = dataclasses.replace(cfg, expert_axis=None, ep_size=1,
                                  model_axis=None, tp_size=1)
    tx = sgd_with_weight_decay(0.1, momentum=0.9)
    mesh = make_mesh(devices8, data_parallel=2, seq_parallel=2,
                     model_parallel=2,
                     axis_names=("data", "stage", "model"))
    n_stages = 2
    state0 = create_pp_lm_state(cfg, n_stages, tx, jax.random.key(4),
                                init_len=32)
    state_ref = create_pp_lm_state(cfg_ref, n_stages, tx, jax.random.key(4),
                                   init_len=32)
    state_pp, specs = shard_pp_state(mesh, state0, axis="stage", config=cfg)
    # the combined placement: stack on stage, experts on data, hidden on model
    for lname in ("layer0", "layer1"):
        w_up_spec = specs.params["stages"][lname]["moe"]["w_up"]
        assert w_up_spec == P("stage", "data", None, "model"), (lname,
                                                                w_up_spec)
    step_pp = make_pp_lm_train_step(mesh, cfg, specs, n_microbatches=2,
                                    axis="stage")
    step_ref = make_pp_reference_step(cfg_ref, n_stages, tx, n_microbatches=2)
    sh = NamedSharding(mesh, P("data"))
    for i in range(3):
        b = batch_np(seed=40 + i)
        state_pp, m_pp = step_pp(
            state_pp, {k: jax.device_put(v, sh) for k, v in b.items()}
        )
        state_ref, m_ref = step_ref(state_ref, b)
        np.testing.assert_allclose(float(m_pp["loss"]), float(m_ref["loss"]),
                                   rtol=2e-4)
    flat_ref = {str(p): v for p, v in
                jax.tree_util.tree_leaves_with_path(
                    jax.device_get(state_ref.params))}
    for path, leaf in jax.tree_util.tree_leaves_with_path(
            jax.device_get(state_pp.params)):
        np.testing.assert_allclose(
            np.asarray(leaf), np.asarray(flat_ref[str(path)]),
            rtol=2e-3, atol=2e-4, err_msg=str(path),
        )


def test_pp_ep_specs_without_config_rejected(devices8):
    """shard_pp_state without config= builds replicated expert specs; the
    step must name the mistake instead of failing deep in flax."""
    cfg = tiny_config(num_layers=4, n_experts=2, moe_every=1,
                      expert_axis="data", ep_size=2)
    tx = sgd_with_weight_decay(0.1)
    mesh = make_mesh(devices8, data_parallel=2, seq_parallel=1,
                     model_parallel=N_STAGES)
    state = create_pp_lm_state(cfg, N_STAGES, tx, jax.random.key(0),
                               init_len=16)
    _, specs = shard_pp_state(mesh, state)  # config forgotten
    with pytest.raises(ValueError, match="EP placement rules"):
        make_pp_lm_train_step(mesh, cfg, specs)
