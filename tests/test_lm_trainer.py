"""LMTrainer end-to-end on a dp×sp×tp mesh: epoch loop, perplexity eval,
suspend/resume bit-parity with a TP-sharded state, deterministic dropout
(VERDICT r1 missing #6/#8/#9, weak #4)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow

from pytorch_distributed_tpu.data.tokens import SyntheticTokens, TokenArrayDataset
from pytorch_distributed_tpu.models.transformer import TransformerLM, tiny_config
from pytorch_distributed_tpu.parallel import make_mesh
from pytorch_distributed_tpu.train import LMTrainer, LMTrainerConfig
from conftest import FireAtStep  # noqa: E402


def lm_cfg(**over):
    # ring attention: the mesh below shards the sequence axis
    base = dict(attention="ring", model_axis="model", tp_size=2, dropout=0.1)
    base.update(over)
    return tiny_config(**base)


# LMTrainerConfig field names (trainer knobs); everything else in
# make_lm_trainer's **cfg_over goes to the MODEL config
_TRAINER_FIELDS = {f.name for f in __import__("dataclasses").fields(LMTrainerConfig)}


def make_lm_trainer(save_dir, devices8, watcher=None, **cfg_over):
    mesh = make_mesh(devices8, data_parallel=2, seq_parallel=2,
                     model_parallel=2)
    base = dict(epochs=2, batch_size=2, lr=1e-2, save_dir=str(save_dir),
                num_workers=0, log_every=1, warmup_steps=0)
    base.update(
        {k: cfg_over.pop(k) for k in list(cfg_over) if k in _TRAINER_FIELDS}
    )
    cfg = LMTrainerConfig(**base)
    train = SyntheticTokens(size=16, seq_len=32, vocab_size=128)
    val = SyntheticTokens(size=8, seq_len=32, vocab_size=128, seed=9)
    return LMTrainer(lm_cfg(**cfg_over), train, val, cfg, mesh=mesh,
                     suspend_watcher=watcher)


from conftest import assert_trees_equal as params_equal  # noqa: E402


def test_token_array_dataset_windows():
    toks = np.arange(100, dtype=np.int64)
    ds = TokenArrayDataset(toks, seq_len=32)
    assert len(ds) == 3
    np.testing.assert_array_equal(ds[1], np.arange(32, 64))
    assert ds[0].dtype == np.int32
    with pytest.raises(ValueError):
        TokenArrayDataset(toks[:10], seq_len=32)


def test_lm_trainer_fit_and_ppl(tmp_path, devices8):
    tr = make_lm_trainer(tmp_path / "a", devices8)
    res = tr.fit()
    assert np.isfinite(res["loss"]) and res["ppl"] > 1.0
    # best_ppl tracking: exactly the min of the per-epoch val ppls logged
    import json

    val_ppls = [
        json.loads(line)["ppl"]
        for line in open(os.path.join(str(tmp_path / "a"), "metrics.jsonl"))
        if json.loads(line).get("kind") == "val"
    ]
    assert len(val_ppls) == 2
    assert res["best_ppl"] == pytest.approx(min(val_ppls))
    assert os.path.exists(os.path.join(str(tmp_path / "a"), "best.ckpt"))
    # the TP state really is sharded on the mesh
    qkv = tr.state.params["block0"]["attn"]["qkv"]["kernel"]
    assert len({s.data.shape for s in qkv.addressable_shards}) == 1
    shard = next(iter(qkv.addressable_shards)).data.shape
    assert shard[2] == qkv.shape[2] // 2  # heads dim split over model axis


def test_lm_suspend_resume_bit_parity(tmp_path, devices8):
    """Mirror of the image trainer's bit-parity test, with dropout ON and a
    TP/SP-sharded state: an interrupted+resumed run must equal the
    uninterrupted one bit for bit — dropout masks keyed by (seed, step)
    included."""
    t_ref = make_lm_trainer(tmp_path / "ref", devices8)
    t_ref.fit()

    t_int = make_lm_trainer(tmp_path / "int", devices8, watcher=FireAtStep(7))
    with pytest.raises(SystemExit):
        t_int.fit()
    assert t_int.ckpt.has_latest()

    t_res = make_lm_trainer(tmp_path / "int", devices8)
    t_res.fit()
    params_equal(t_ref.state.params, t_res.state.params)
    assert int(jax.device_get(t_ref.state.step)) == int(
        jax.device_get(t_res.state.step)
    )


def test_dropout_train_vs_eval():
    cfg = tiny_config(dropout=0.5)
    model = TransformerLM(cfg)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(1, 128, (2, 16)), jnp.int32
    )
    variables = model.init(jax.random.key(0), tokens, train=False)
    out_eval = model.apply(variables, tokens, train=False)
    out_eval2 = model.apply(variables, tokens, train=False)
    np.testing.assert_array_equal(np.asarray(out_eval), np.asarray(out_eval2))
    key = jax.random.key(1)
    out_tr = model.apply(variables, tokens, train=True, rngs={"dropout": key})
    out_tr_same = model.apply(variables, tokens, train=True,
                              rngs={"dropout": key})
    out_tr_other = model.apply(variables, tokens, train=True,
                               rngs={"dropout": jax.random.key(2)})
    np.testing.assert_array_equal(np.asarray(out_tr), np.asarray(out_tr_same))
    assert not np.allclose(np.asarray(out_tr), np.asarray(out_eval))
    assert not np.allclose(np.asarray(out_tr), np.asarray(out_tr_other))


def test_dropout_zero_is_identity_with_round1_behavior():
    """dropout=0 must add no rng requirement and no Dropout modules (param
    tree unchanged vs a config that never mentions dropout)."""
    cfg0 = tiny_config()
    cfgz = tiny_config(dropout=0.0)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(1, 128, (2, 16)), jnp.int32
    )
    v0 = TransformerLM(cfg0).init(jax.random.key(0), tokens)
    vz = TransformerLM(cfgz).init(jax.random.key(0), tokens)
    assert jax.tree.structure(v0) == jax.tree.structure(vz)
    np.testing.assert_array_equal(
        np.asarray(TransformerLM(cfg0).apply(v0, tokens, train=True)),
        np.asarray(TransformerLM(cfgz).apply(vz, tokens, train=True)),
    )


def test_dropout_config_validation():
    with pytest.raises(ValueError, match="dropout"):
        tiny_config(dropout=1.5)
