"""Resilience runtime: fault injection, guards, fallback restore, and the
crash-recovery kill-matrix.

Fast tier: the deterministic fault plan, bounded retry, stepguard
skip/rollback semantics (through the real compiled steps), watchdog stall
handling, checkpoint validation + fallback restore, and retention
boundaries. Slow tier (``@slow @crash``): the subprocess kill-matrix —
SIGKILL a real training run at each checkpoint hazard site
{mid-shard-write, pre-manifest-commit, post-commit}, relaunch, and assert
it resumes from a complete checkpoint with monotonic step count and
finite loss.
"""

import json
import os
import shutil
import signal
import subprocess
import sys
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_tpu.resilience import faults
from pytorch_distributed_tpu.resilience.faults import (
    FaultPlan,
    FaultSpec,
    InjectedFault,
    poison_batch,
)
from pytorch_distributed_tpu.resilience.retry import (
    backoff_delays,
    retry_call,
)
from pytorch_distributed_tpu.resilience.stepguard import (
    RollbackRequested,
    StepGuard,
    finite_ok,
)
from pytorch_distributed_tpu.resilience.watchdog import Watchdog
from pytorch_distributed_tpu.utils.checkpoint import (
    MANIFEST,
    Checkpointer,
    validate_checkpoint,
)
from pytorch_distributed_tpu.utils.suspend import SuspendWatcher


@pytest.fixture(autouse=True)
def _no_leaked_plan():
    """Every test starts and ends without an installed fault plan."""
    faults.clear_plan()
    yield
    faults.clear_plan()


def plan(*specs) -> FaultPlan:
    return faults.install_plan(FaultPlan([FaultSpec(**s) for s in specs]))


# ---------------------------------------------------------------------------
# fault plan


def test_fault_plan_json_roundtrip_and_occurrence_window():
    p = FaultPlan.from_json(
        '{"faults": [{"site": "s", "kind": "raise", "at": 1, "times": 2}]}'
    )
    p2 = FaultPlan.from_json(p.to_json())
    assert [s.site for s in p2.specs] == ["s"]
    # occurrences 0, 3+ pass; 1 and 2 fire
    assert p2.tick("s") is None
    assert p2.tick("s").kind == "raise"
    assert p2.tick("s").kind == "raise"
    assert p2.tick("s") is None
    assert p2.fired == [("s", 1, "raise"), ("s", 2, "raise")]
    # unknown sites never match and don't disturb the counter
    assert p2.tick("other") is None


def test_fault_plan_from_env_file(tmp_path, monkeypatch):
    path = tmp_path / "plan.json"
    path.write_text('{"faults": [{"site": "x", "kind": "hang"}]}')
    monkeypatch.setenv(faults.ENV_PLAN, f"@{path}")
    faults.clear_plan()  # force the env re-read
    p = faults.active_plan()
    assert p is not None and p.specs[0].site == "x"


def test_fault_point_raises_injected():
    plan({"site": "data.fetch", "kind": "raise"})
    with pytest.raises(InjectedFault):
        faults.fault_point("data.fetch")
    # windows are bounded: the next occurrence passes
    assert faults.fault_point("data.fetch") is None


def test_fault_spec_validates():
    with pytest.raises(ValueError):
        FaultSpec(site="s", kind="explode")
    with pytest.raises(ValueError):
        FaultSpec(site="s", kind="raise", times=0)


def test_poison_batch_nans_floats_only():
    batch = {"tokens": np.arange(4, dtype=np.int32),
             "weights": np.ones(4, np.float32)}
    out = poison_batch(batch)
    assert np.isnan(out["weights"]).all()
    np.testing.assert_array_equal(out["tokens"], batch["tokens"])
    with pytest.raises(ValueError):
        poison_batch({"tokens": np.arange(4, dtype=np.int32)})


# ---------------------------------------------------------------------------
# retry


def test_backoff_delays_deterministic_bounded():
    a = backoff_delays(retries=4, base_delay=0.1, max_delay=0.5, seed=7)
    b = backoff_delays(retries=4, base_delay=0.1, max_delay=0.5, seed=7)
    assert a == b  # seeded: same schedule every run
    assert a != backoff_delays(retries=4, base_delay=0.1, max_delay=0.5,
                               seed=8)
    assert all(0 < d <= 0.5 for d in a)


def test_retry_call_recovers_then_exhausts(monkeypatch):
    import pytorch_distributed_tpu.resilience.retry as retry_mod

    sleeps = []
    monkeypatch.setattr(retry_mod.time, "sleep", sleeps.append)
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError("transient")
        return "ok"

    assert retry_call(flaky, retries=3) == "ok"
    assert calls["n"] == 3 and len(sleeps) == 2

    def always():
        raise OSError("permanent")

    with pytest.raises(OSError, match="permanent"):
        retry_call(always, retries=2)

    class Structural(OSError):
        pass

    def structural():
        raise Structural("no point retrying")

    with pytest.raises(Structural):
        retry_call(structural, retries=3, no_retry_on=(Structural,))
    # TypeError is not in retry_on: first raise propagates
    calls["n"] = 0

    def bug():
        calls["n"] += 1
        raise TypeError("bug")

    with pytest.raises(TypeError):
        retry_call(bug, retries=3)
    assert calls["n"] == 1


def test_record_reader_retries_transient_pread(tmp_path, monkeypatch):
    from pytorch_distributed_tpu.data.packed_record import (
        PackedRecordReader,
        PackedRecordWriter,
    )

    path = tmp_path / "r.tprc"
    with PackedRecordWriter(path) as w:
        w.write(b"hello")
    reader = PackedRecordReader(path, use_native=False)
    monkeypatch.setattr(
        "pytorch_distributed_tpu.resilience.retry.time.sleep", lambda s: None
    )
    real = reader._py.read
    fails = {"n": 2}

    def flaky(i, verify_crc=True):
        if fails["n"]:
            fails["n"] -= 1
            raise OSError("pread failover")
        return real(i, verify_crc)

    monkeypatch.setattr(reader._py, "read", flaky)
    assert reader.read(0) == b"hello"  # two failures absorbed
    reader.close()


# ---------------------------------------------------------------------------
# data loader: fetch faults + teardown


def _range_loader(**kw):
    from pytorch_distributed_tpu.data.loader import DataLoader

    class Toy:
        def __len__(self):
            return 16

        def __getitem__(self, i):
            return np.full((2, 2, 3), i, np.float32), i % 4

    return DataLoader(Toy(), batch_size=4, num_workers=0, **kw)


def test_loader_retries_fetch_faults(monkeypatch):
    monkeypatch.setattr(
        "pytorch_distributed_tpu.resilience.retry.time.sleep", lambda s: None
    )
    p = plan({"site": "data.fetch", "kind": "raise", "at": 1, "times": 2})
    batches = list(_range_loader(prefetch=1).iter_batches(0))
    assert len(batches) == 4  # both injected failures absorbed by retry
    assert len(p.fired) == 2
    # the re-fetched batch is bit-identical (deterministic RNG/data)
    clean = list(_range_loader(prefetch=1).iter_batches(0))
    np.testing.assert_array_equal(batches[1]["image"], clean[1]["image"])


def test_loader_fetch_fault_beyond_retries_raises(monkeypatch):
    monkeypatch.setattr(
        "pytorch_distributed_tpu.resilience.retry.time.sleep", lambda s: None
    )
    plan({"site": "data.fetch", "kind": "raise", "times": 50})
    with pytest.raises(InjectedFault):
        list(_range_loader(prefetch=1).iter_batches(0))
    faults.clear_plan()
    # prefetch path: the producer thread surfaces the failure too
    plan({"site": "data.fetch", "kind": "raise", "times": 50})
    with pytest.raises(InjectedFault):
        list(_range_loader(prefetch=2).iter_batches(0))


def test_loader_teardown_joins_producer_and_cancels_futures():
    """Abandoning a prefetching iterator mid-epoch must leave no live
    producer thread (blocking join, not a poll loop) and no queued decode
    futures."""
    loader = _range_loader(prefetch=2)
    loader.num_workers = 2  # exercise the pool-backed path
    before = {t.ident for t in threading.enumerate()}
    it = loader.iter_batches(0)
    next(it)
    it.close()  # generator finally: drain, join, shutdown(cancel_futures)
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        leaked = {t.ident for t in threading.enumerate()} - before
        if not leaked:
            break
        time.sleep(0.01)
    assert not leaked, f"leaked threads: {leaked}"


# ---------------------------------------------------------------------------
# stepguard


def test_finite_ok_under_jit():
    @jax.jit
    def check(loss, g):
        return finite_ok(loss, {"w": g})

    assert bool(check(jnp.float32(1.0), jnp.ones(3)))
    assert not bool(check(jnp.float32(np.nan), jnp.ones(3)))
    assert not bool(check(jnp.float32(1.0), jnp.array([1.0, np.inf, 0.0])))
    # integer leaves don't participate in the finite check
    assert bool(finite_ok(jnp.float32(0.0), {"i": jnp.arange(3)}))


def test_stepguard_counts_and_rolls_back():
    g = StepGuard(max_bad_steps=3, lag=1)
    good, bad = jnp.float32(1.0), jnp.float32(0.0)
    g.observe(good)
    g.observe(bad)   # reads the lagged good
    g.observe(bad)   # reads bad #1
    g.observe(bad)   # reads bad #2
    assert g.bad_consecutive == 2 and g.bad_total == 2
    with pytest.raises(RollbackRequested):
        g.flush()    # bad #3 trips the limit
    assert g.rollbacks == 1 and g.bad_consecutive == 0
    # a good step resets the streak
    g2 = StepGuard(max_bad_steps=2, lag=0)
    g2.observe(bad)
    g2.observe(good)
    g2.observe(bad)
    assert g2.bad_consecutive == 1 and g2.bad_total == 2
    g2.reset()
    assert g2.bad_consecutive == 0


def test_stepguard_without_limit_never_raises():
    g = StepGuard(max_bad_steps=0, lag=0)
    for _ in range(10):
        g.observe(jnp.float32(0.0))
    assert g.bad_total == 10
    g.observe(None)  # steps without the metric are ignored
    assert g.bad_total == 10


# ---------------------------------------------------------------------------
# trainers under injected NaN (the real compiled steps)


def test_nan_steps_skip_update_and_freeze_params(tmp_path, devices8):
    """Every train step poisoned: with the guard, params at the end equal
    params at the start bit-for-bit (each bad step selected the old
    state), step still advanced per consumed batch, and no host-side NaN
    ever reached the parameters."""
    from test_train import make_trainer

    plan({"site": "train.step", "kind": "nan", "times": 10_000})
    trainer = make_trainer(tmp_path, devices8, epochs=1,
                           nan_guard=True)
    before = jax.device_get(trainer.state.params)
    steps = len(trainer.train_loader)
    trainer.fit()
    after = jax.device_get(trainer.state.params)
    for a, b in zip(jax.tree.leaves(before), jax.tree.leaves(after)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert trainer.guard.bad_total == steps
    assert int(jax.device_get(trainer.state.step)) == steps  # step advanced


def test_single_nan_step_recovers_and_counts(tmp_path, devices8):
    from test_train import make_trainer

    p = plan({"site": "train.step", "kind": "nan", "at": 2})
    trainer = make_trainer(tmp_path, devices8, epochs=1, nan_guard=True)
    out = trainer.fit()
    assert p.fired == [("train.step", 2, "nan")]
    assert trainer.guard.bad_total == 1
    assert np.isfinite(out["loss"])
    for leaf in jax.tree.leaves(jax.device_get(trainer.state.params)):
        assert np.isfinite(np.asarray(leaf)).all()


def test_consecutive_nans_roll_back_to_checkpoint(tmp_path, devices8):
    """K consecutive bad steps trigger rollback-to-last-good-checkpoint:
    the run restores an interval save, replays, and finishes finite."""
    from test_train import make_trainer

    plan({"site": "train.step", "kind": "nan", "at": 3, "times": 6})
    trainer = make_trainer(
        tmp_path, devices8, epochs=1, nan_guard=True, max_bad_steps=3,
        save_every_n_steps=1, keep_last_ckpts=2,
    )
    out = trainer.fit()
    assert trainer.rollbacks >= 1
    assert trainer.guard.bad_total >= 3
    assert np.isfinite(out["loss"])
    assert int(jax.device_get(trainer.state.step)) == len(
        trainer.train_loader
    )


def test_rollback_without_checkpoint_is_fatal(tmp_path, devices8):
    from test_train import make_trainer

    plan({"site": "train.step", "kind": "nan", "times": 10_000})
    trainer = make_trainer(tmp_path, devices8, epochs=1, nan_guard=True,
                           max_bad_steps=2)
    with pytest.raises(RuntimeError, match="no restorable checkpoint"):
        trainer.fit()


@pytest.mark.slow
def test_lm_trainer_nan_guard_on_tp_mesh(tmp_path, devices8):
    """The LM step's finite gate on a dp×sp×tp mesh: the pmin over every
    mesh axis must veto the update globally even though TP gradient
    shards differ per device."""
    from test_lm_trainer import make_lm_trainer

    p = plan({"site": "train.step", "kind": "nan", "at": 1})
    trainer = make_lm_trainer(tmp_path, devices8, epochs=1, nan_guard=True)
    out = trainer.fit()
    assert p.fired == [("train.step", 1, "nan")]
    assert trainer.guard.bad_total == 1
    assert np.isfinite(out["loss"])
    for leaf in jax.tree.leaves(jax.device_get(trainer.state.params)):
        assert np.isfinite(np.asarray(leaf)).all()


# ---------------------------------------------------------------------------
# watchdog


def test_watchdog_dumps_stacks_and_latches_suspend(tmp_path):
    dump = tmp_path / "stall.log"
    watcher = SuspendWatcher(install_handlers=False)
    stalls = []
    wd = Watchdog(0.2, watcher=watcher, dump_path=str(dump),
                  on_stall=stalls.append, poll_s=0.05)
    with wd:
        wd.beat()
        time.sleep(0.7)  # no beats: stall
        assert wd.stalls == 1  # one dump per stall, not one per poll
        wd.beat()  # re-arms
    assert watcher.receive_suspend_command()
    assert stalls and "pdt-watchdog" in stalls[0]  # all threads dumped
    text = dump.read_text()
    assert "watchdog stall #1" in text and "MainThread" in text


def test_watchdog_rejects_nonpositive_timeout():
    with pytest.raises(ValueError):
        Watchdog(0.0)


def test_hang_triggers_watchdog_then_suspend_checkpoint(tmp_path, devices8):
    """A synthetic hang inside the step loop: the watchdog dumps stacks
    and latches the suspend watcher; the loop (a SOFT stall — it
    recovers) then checkpoints and yields through the normal suspend
    path. The whole §3.5 contract, provoked by injection."""
    from test_train import make_trainer

    plan({"site": "train.step", "kind": "hang", "at": 2, "seconds": 1.2})
    trainer = make_trainer(
        tmp_path, devices8, epochs=1,
        watcher=SuspendWatcher(install_handlers=False),
        watchdog_timeout_s=0.3,
    )
    try:
        with pytest.raises(SystemExit):
            trainer.fit()
    finally:
        trainer.watchdog.stop()
    assert trainer.watchdog.stalls >= 1
    assert trainer.ckpt.latest_is_sharded()  # suspend save committed
    assert os.path.exists(
        os.path.join(str(tmp_path), "watchdog_stall.log")
    )


# ---------------------------------------------------------------------------
# checkpoint validation, fallback restore, retention


def _payload(step):
    return {
        "state": {"step": jnp.asarray(step, jnp.int32),
                  "w": jnp.full((4, 4), float(step))},
        "epoch": 0, "step": step,
    }


def _shard_files(d):
    return sorted(
        n for n in os.listdir(d) if n.startswith("shard-")
        and n.endswith(".npz")
    )


def test_validate_checkpoint_classifies_damage(tmp_path):
    ck = Checkpointer(str(tmp_path))
    ck.save_step_sharded(_payload(1), 1, keep_last=4, block=True)
    d = os.path.join(str(tmp_path), "step-00000001.ckpt")
    assert validate_checkpoint(d) == []
    # truncated shard (torn write): zip central directory lost
    shard = os.path.join(d, _shard_files(d)[0])
    blob = open(shard, "rb").read()
    with open(shard, "wb") as f:
        f.write(blob[: len(blob) // 2])
    assert any("unreadable" in p for p in validate_checkpoint(d))
    # missing shard file
    os.remove(shard)
    assert any("missing shard" in p for p in validate_checkpoint(d))
    # no manifest at all
    os.remove(os.path.join(d, MANIFEST))
    assert any("no manifest" in p.lower() for p in validate_checkpoint(d))


def test_newest_restorable_falls_back_past_torn_save(tmp_path):
    """The newest checkpoint fails validation (truncated shard / token
    mismatch) → resume scans back to the newest COMPLETE one instead of
    refusing (the fallback-restore contract)."""
    d = str(tmp_path)
    ck = Checkpointer(d)
    ck.save_step_sharded(_payload(1), 1, keep_last=4, block=True)
    ck.save_step_sharded(_payload(2), 2, keep_last=4, block=True)
    newest = os.path.join(d, "step-00000002.ckpt")
    assert ck.newest_restorable() == newest
    # truncate the newest save's shard
    shard = os.path.join(newest, _shard_files(newest)[0])
    blob = open(shard, "rb").read()
    with open(shard, "wb") as f:
        f.write(blob[: len(blob) // 2])
    assert ck.newest_restorable() == os.path.join(d, "step-00000001.ckpt")


def test_newest_restorable_rejects_token_mismatch(tmp_path):
    """A shard file from a DIFFERENT save behind this manifest (the torn
    state the save token exists to catch) fails validation and falls
    through to the older checkpoint."""
    d = str(tmp_path)
    ck = Checkpointer(d)
    ck.save_step_sharded(_payload(1), 1, keep_last=4, block=True)
    ck.save_step_sharded(_payload(2), 2, keep_last=4, block=True)
    old = os.path.join(d, "step-00000001.ckpt")
    new = os.path.join(d, "step-00000002.ckpt")
    # splice save 1's shard under save 2's expected filename
    shutil.copyfile(
        os.path.join(old, _shard_files(old)[0]),
        os.path.join(new, _shard_files(new)[0]),
    )
    assert any("token" in p for p in validate_checkpoint(new))
    assert ck.newest_restorable() == old


def test_retention_exact_boundaries_and_inflight_survival(tmp_path):
    """keep_last GC: exactly N completed checkpoints survive, and an
    in-flight (uncommitted) save is never counted or collected — the GC
    runs only after the new manifest landed."""
    d = str(tmp_path)
    ck = Checkpointer(d)
    for s in (1, 2, 3):
        ck.save_step_sharded(_payload(s), s, keep_last=2, block=True)
    names = sorted(
        n for n in os.listdir(d) if n.startswith("step-")
    )
    assert names == ["step-00000002.ckpt", "step-00000003.ckpt"]
    # in-flight: non-blocking save — before wait() commits it, every
    # already-completed checkpoint must still be present
    ck.save_step_sharded(_payload(4), 4, keep_last=1, block=False)
    assert os.path.exists(os.path.join(d, "step-00000002.ckpt"))
    assert os.path.exists(os.path.join(d, "step-00000003.ckpt"))
    ck.wait()  # commit + GC
    names = sorted(
        n for n in os.listdir(d)
        if n.startswith("step-")
        and os.path.exists(os.path.join(d, n, MANIFEST))
    )
    assert names == ["step-00000004.ckpt"]


def test_trainer_resume_falls_back_when_newest_corrupt(tmp_path, devices8):
    """End-to-end fallback: a fit leaves interval saves; the newest one is
    torn after the fact; a fresh trainer resumes from the older complete
    checkpoint instead of refusing."""
    from test_train import make_trainer

    t1 = make_trainer(tmp_path, devices8, epochs=1,
                      save_every_n_steps=2, keep_last_ckpts=2)
    t1.fit()
    ck = Checkpointer(str(tmp_path))
    steps = ck.step_checkpoints()
    assert len(steps) == 2
    newest = steps[-1][1]
    shard = os.path.join(newest, _shard_files(newest)[0])
    blob = open(shard, "rb").read()
    with open(shard, "wb") as f:
        f.write(blob[: len(blob) // 2])
    t2 = make_trainer(tmp_path, devices8, epochs=1,
                      save_every_n_steps=2, keep_last_ckpts=2)
    assert t2.try_resume()
    assert int(jax.device_get(t2.state.step)) == steps[0][0]


# ---------------------------------------------------------------------------
# the kill-matrix (slow): SIGKILL at each checkpoint hazard site, relaunch,
# assert recovery. scripts/ci_check.sh --resilience-smoke runs the
# shard_write cell alone.

KILL_SITES = ["ckpt.shard_write", "ckpt.pre_commit", "ckpt.post_commit"]


def _run_child(save_dir, env_extra=None, timeout=300):
    env = dict(os.environ)
    env.pop(faults.ENV_PLAN, None)
    env.update(env_extra or {})
    return subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(__file__), "crash_child.py"),
         "--save-dir", str(save_dir)],
        env=env, capture_output=True, text=True, timeout=timeout,
    )


def _progress(save_dir):
    path = os.path.join(str(save_dir), "progress.jsonl")
    with open(path) as f:
        return [json.loads(line) for line in f]


@pytest.mark.slow
@pytest.mark.crash
@pytest.mark.parametrize("site", KILL_SITES, ids=lambda s: s.split(".")[1])
def test_kill_matrix_sigkill_then_resume(tmp_path, site):
    """Run 1 dies by SIGKILL at the injected checkpoint hazard; the
    directory must hold a complete (old or new, never corrupt)
    checkpoint; run 2 resumes from it and finishes with monotonic global
    step and finite loss."""
    fault = FaultPlan([
        # occurrence 2: at least two saves committed before the kill, so
        # recovery has a guaranteed fallback even at mid-write
        FaultSpec(site=site, kind="kill", at=2)
    ])
    r1 = _run_child(tmp_path, {faults.ENV_PLAN: fault.to_json()})
    assert r1.returncode == -signal.SIGKILL, (
        f"child should die by SIGKILL at {site}; "
        f"rc={r1.returncode}\nstdout:{r1.stdout}\nstderr:{r1.stderr}"
    )
    assert not os.path.exists(os.path.join(str(tmp_path), "result.json"))
    steps_run1 = [r["gstep"] for r in _progress(tmp_path)]
    assert steps_run1  # it trained before dying

    # the invariant the whole checkpointer design promises: whatever the
    # kill point, a complete restorable checkpoint exists and validates
    ck = Checkpointer(str(tmp_path))
    restorable = ck.newest_restorable()
    assert restorable is not None
    assert validate_checkpoint(restorable) == []

    r2 = _run_child(tmp_path)
    assert r2.returncode == 0, (
        f"relaunch failed\nstdout:{r2.stdout}\nstderr:{r2.stderr}"
    )
    with open(os.path.join(str(tmp_path), "result.json")) as f:
        result = json.load(f)
    assert result["resumed"], "run 2 must restore a checkpoint"
    assert np.isfinite(result["val_loss"])

    records = _progress(tmp_path)
    pid2 = records[-1]["pid"]
    steps_run2 = [r["gstep"] for r in records if r["pid"] == pid2]
    # monotonic step count within the resumed run, no gaps
    assert steps_run2 == list(
        range(steps_run2[0], steps_run2[0] + len(steps_run2))
    )
    # resumed at (not past) work already done: first step of run 2
    # continues from a checkpoint at or before run 1's last step
    assert steps_run2[0] <= steps_run1[-1] + 1
    # and the full run completed: 2 epochs x 2 steps at the child config
    assert result["final_step"] == 4
    assert all(np.isfinite(r["loss"]) for r in records if r["pid"] == pid2)


def test_bench_retry_transient(monkeypatch):
    """VERDICT r5 ``lm_error``: one transient remote-compile HTTP 500
    erased a round's headline number. ``bench.retry_transient`` retries
    transient markers on the deterministic backoff schedule, propagates
    non-transient errors immediately, and re-raises after exhaustion."""
    import os
    import sys
    import time as time_mod

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    try:
        import bench
    finally:
        sys.path.pop(0)

    assert bench._is_transient(RuntimeError("HTTP/1.1 500 oops"))
    assert bench._is_transient(RuntimeError("UNAVAILABLE: socket"))
    # real OOM is handled by batch halving, never retried
    assert not bench._is_transient(RuntimeError("RESOURCE_EXHAUSTED"))
    assert not bench._is_transient(ValueError("shape mismatch"))

    sleeps = []
    monkeypatch.setattr(time_mod, "sleep", sleeps.append)

    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise RuntimeError("Internal Server Error")
        return 41

    assert bench.retry_transient(flaky, retries=2) == 41
    assert calls["n"] == 3 and len(sleeps) == 2

    def hard():
        raise ValueError("not transient")

    with pytest.raises(ValueError):
        bench.retry_transient(hard, retries=2)

    def always():
        raise RuntimeError("Bad Gateway")

    with pytest.raises(RuntimeError, match="Bad Gateway"):
        bench.retry_transient(always, retries=1)
