"""Tensor-parallel ragged serving (VERDICT r4 next #5): the continuous
batcher and generate_ragged run under shard_map over the model axis —
KV cache head-sharded, Megatron collectives inside each program —
pinned token-exact against the replicated serving path."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow

from pytorch_distributed_tpu.models.generate import (  # noqa: E402
    ContinuousBatcher,
    generate_ragged,
    generate_ragged_tp,
)
from pytorch_distributed_tpu.models.transformer import (  # noqa: E402
    TransformerLM,
    tiny_config,
)
from pytorch_distributed_tpu.parallel import make_mesh  # noqa: E402


def setup(tp=2, **over):
    rep = tiny_config(attention="dense", max_seq_len=96, num_heads=4,
                      **over)
    vp = dataclasses.replace(rep, model_axis="model", tp_size=tp)
    params = TransformerLM(rep).init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    mesh = make_mesh(jax.devices()[:tp], data_parallel=1, seq_parallel=1,
                     model_parallel=tp)
    return rep, vp, params, mesh


def _ragged_inputs(cfg, lengths, pad_to=32):
    rng = np.random.default_rng(0)
    prompts = np.zeros((len(lengths), pad_to), np.int32)
    for i, l in enumerate(lengths):
        prompts[i, :l] = rng.integers(1, cfg.vocab_size, (l,))
    return jnp.asarray(prompts), jnp.asarray(lengths, jnp.int32)


@pytest.mark.parametrize("kv_heads", [None, 2])
def test_generate_ragged_tp_parity(kv_heads):
    rep, tpcfg, params, mesh = setup(num_kv_heads=kv_heads)
    prompts, lengths = _ragged_inputs(rep, [5, 17, 32, 9])
    out_rep = generate_ragged(rep, params, prompts, lengths,
                              jax.random.key(1), max_new_tokens=8)
    out_tp = generate_ragged_tp(mesh, tpcfg, params, prompts, lengths,
                                jax.random.key(1), max_new_tokens=8)
    np.testing.assert_array_equal(np.asarray(out_tp), np.asarray(out_rep))


def test_generate_ragged_tp_vocab_parallel_parity():
    rep, tpcfg, params, mesh = setup()
    vp = dataclasses.replace(tpcfg, vocab_parallel=True)
    prompts, lengths = _ragged_inputs(rep, [5, 17])
    out_rep = generate_ragged(rep, params, prompts, lengths,
                              jax.random.key(1), max_new_tokens=8)
    out_vp = generate_ragged_tp(mesh, vp, params, prompts, lengths,
                                jax.random.key(1), max_new_tokens=8)
    np.testing.assert_array_equal(np.asarray(out_vp), np.asarray(out_rep))


def _drive(batcher, prompts_list, max_new, eos=None):
    """Deterministic submit/step schedule; returns {req: [tokens]}."""
    produced = {}
    pending = list(enumerate(prompts_list))
    slot_req = {}
    while pending or any(batcher.remaining > 0):
        while pending and batcher.free_slots():
            req, p = pending.pop(0)
            slot = batcher.submit(p, max_new)
            slot_req[slot] = req
            produced[req] = []
        for slot, tok in batcher.step():
            produced[slot_req[slot]].append(tok)
    return produced


def test_batcher_tp_parity_vs_replicated():
    """Same submit/step schedule, same seeds: the TP batcher must emit
    token-identical streams — including slot retirement and reuse."""
    rep, tpcfg, params, mesh = setup()
    rng = np.random.default_rng(2)
    prompts = [
        rng.integers(1, rep.vocab_size, (l,)).astype(np.int32)
        for l in (5, 11, 7, 3)
    ]
    b_rep = ContinuousBatcher(rep, params, n_slots=2, prefill_bucket=8)
    b_tp = ContinuousBatcher(tpcfg, params, n_slots=2, prefill_bucket=8,
                             mesh=mesh)
    out_rep = _drive(b_rep, prompts, 6)
    out_tp = _drive(b_tp, prompts, 6)
    assert out_rep == out_tp
    # the TP cache really is head-sharded at rest
    leaf = jax.tree.leaves(b_tp.cache)[0]
    assert next(iter(leaf.addressable_shards)).data.shape[2] == \
        leaf.shape[2] // 2


def test_batcher_tp_requires_mesh():
    rep, tpcfg, params, _mesh = setup()
    with pytest.raises(ValueError, match="mesh"):
        ContinuousBatcher(tpcfg, params, n_slots=2)
