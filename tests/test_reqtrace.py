"""Request-lifecycle causal tracing (round 14 tentpole): span trees
across admission → prefill → handoff → decode → preempt → restore, the
completeness validator, the explain_request forensics CLI, the Perfetto
exporter, the JSONL schema registry, SpanTracer's per-thread stacks, and
the Prometheus exporter under concurrent scrapes."""

import json
import os
import threading
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_tpu.analysis.core import LintContext, parse_file
from pytorch_distributed_tpu.analysis.rules_threads import check_threads
from pytorch_distributed_tpu.fleet import FleetRouter
from pytorch_distributed_tpu.fleet.admission import (
    SHED,
    Decision,
    trace_decision,
)
from pytorch_distributed_tpu.models.transformer import (
    TransformerLM,
    tiny_config,
)
from pytorch_distributed_tpu.serving import Scheduler
from pytorch_distributed_tpu.telemetry import (
    NULL_REQTRACER,
    AnomalySentinel,
    MetricsExporter,
    ReqTracer,
    SpanTracer,
    build_tree,
    chrome_trace,
    validate_stream,
    validate_trace,
)
from pytorch_distributed_tpu.telemetry.reqtrace import span_records
from pytorch_distributed_tpu.utils.profiling import MetricsLogger

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _import_script(name):
    """Import a scripts/ module without leaving scripts/ on sys.path."""
    import importlib
    import sys

    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        return importlib.import_module(name)
    finally:
        sys.path.pop(0)


@pytest.fixture(scope="module")
def model():
    cfg = tiny_config(attention="dense", max_seq_len=64)
    params = TransformerLM(cfg).init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return cfg, params


def _prompts(lens, cfg, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(1, cfg.vocab_size, size=l).astype(np.int32)
            for l in lens]


@pytest.fixture(scope="module")
def pressure_run(model):
    """Standalone scheduler, forced-swap preemption mid-decode: the
    preempt→park→restore sub-tree with predicted-vs-measured walls."""
    cfg, params = model
    tracer = ReqTracer()
    s = Scheduler(cfg, params, n_slots=2, block_len=8, prefill_chunk=8,
                  offload=True, swap_policy="swap", reqtrace=tracer)
    prompts = _prompts((12, 9), cfg)
    rids = [s.submit(p, 6) for p in prompts]
    streams = {}
    for _ in range(32):  # arm rid0's decode lane, then preempt it
        for rid, tok in s.step():
            streams.setdefault(rid, []).append(tok)
        if streams.get(rids[0]):
            break
    decision = s.preempt(rids[0], reason="test")
    assert decision is not None and decision.choice == "swap"
    for rid, toks in s.drain().items():
        streams.setdefault(rid, []).extend(toks)
    # token identity across the preemption (vs an unpreempted reference)
    ref = Scheduler(cfg, params, n_slots=2, block_len=8, prefill_chunk=8)
    ref_rids = [ref.submit(p, 6) for p in prompts]
    ref_streams = ref.drain()
    assert [streams[r] for r in rids] == [ref_streams[r] for r in ref_rids]
    return tracer.records, rids


@pytest.fixture(scope="module")
def disagg_run(model, tmp_path_factory):
    """Disaggregated 2-replica fleet over a small decode pool: handoff
    spans + flow links, plus the handoff-pressure preempt rung."""
    cfg, params = model
    path = str(tmp_path_factory.mktemp("reqtrace") / "fleet.jsonl")
    mlog = MetricsLogger(path)
    tracer = ReqTracer(mlog, keep=True)
    r = FleetRouter(cfg, params, n_replicas=2, disaggregate=True,
                    metrics_log=mlog, reqtrace=tracer, n_slots=4,
                    block_len=8, prefill_chunk=8, n_blocks=7,
                    offload=True, swap_policy="swap")
    rids = [r.submit(p, 5, session=i)
            for i, p in enumerate(_prompts((12, 14, 9), cfg))]
    r.drain()
    r.log_summary()
    mlog.close()
    with open(path) as f:
        file_records = [json.loads(line) for line in f if line.strip()]
    return tracer.records, file_records, rids, r


# ---------------------------------------------------------------------------
# the trace trees
# ---------------------------------------------------------------------------


def _spans(records, rid, name):
    return [r for r in span_records(records, rid)
            if r.get("name") == name and r.get("ev") == "begin"]


def test_pressure_trace_complete_with_predicted_vs_measured(pressure_run):
    records, rids = pressure_run
    assert validate_trace(records) == []
    rid = rids[0]
    preempts = _spans(records, rid, "preempt")
    assert len(preempts) == 1
    p = preempts[0]
    assert p["decision"] == "swap" and p["predicted_swap_s"] > 0
    # the swap_out close carries measured wall NEXT TO the predicted cost
    swap_out = _spans(records, rid, "swap_out")[0]
    end = next(r for r in span_records(records, rid)
               if r.get("ev") == "end" and r["span"] == swap_out["span"])
    assert end["ok"] and end["wall_s"] > 0
    assert end["predicted_s"] == p["predicted_swap_s"]
    for name in ("parked", "swap_in"):
        assert _spans(records, rid, name), name
    assert any(r.get("name") == "restore" for r in
               span_records(records, rid))
    # two decode windows: the preempted one and the resumed one
    windows = _spans(records, rid, "decode")
    assert len(windows) == 2
    ends = {r["span"]: r for r in span_records(records, rid)
            if r.get("ev") == "end"}
    assert ends[windows[0]["span"]]["outcome"] == "preempted"
    assert windows[1]["resumed"] == "swap"
    # root closed with the stream's outcome
    root = next(r for r in span_records(records, rid)
                if r.get("ev") == "begin" and not r.get("parent"))
    assert ends[root["span"]]["outcome"] == "complete"
    assert ends[root["span"]]["preempts"] == 1


def test_kv_chain_transitions_annotated(pressure_run):
    records, rids = pressure_run
    names = [r["name"] for r in span_records(records, rids[0])
             if r.get("ev") == "event" and r["name"].startswith("kv_")]
    # admission alloc ... swap-out window, free, swap-in realloc ... retire
    assert names[0] == "kv_alloc"
    assert names[-1] == "kv_free"
    states = [r["state"] for r in span_records(records, rids[0])
              if r.get("name") == "kv_state"]
    assert states == ["swapping-out", "resident", "swapping-in",
                      "resident"]


def test_disagg_handoff_is_one_tree_across_replicas(disagg_run):
    records, _file_records, rids, router = disagg_run
    assert validate_trace(records) == []
    for rid in rids:
        handoff = _spans(records, rid, "handoff")
        assert len(handoff) == 1, f"rid {rid}"
        h = handoff[0]
        assert h["src"] == 0 and h["dst"] == 1 and h["bytes"] > 0
        # prefill on r0, the adopted decode window on r1 — one trace
        assert _spans(records, rid, "prefill")[0]["replica"] == 0
        decode = _spans(records, rid, "decode")
        assert decode[0]["replica"] == 1 and decode[0]["adopted"] is True
        # the flow link lands on the adopted decode window
        links = [r for r in span_records(records, rid)
                 if r.get("ev") == "link"]
        assert any(link["span"] == h["span"]
                   and link["dst"] == decode[0]["span"] for link in links)
        # handoff_wait opened on the prefill replica and closed at
        # complete_handoff
        wait = _spans(records, rid, "handoff_wait")
        assert wait and wait[0]["replica"] == 0
    # the small decode pool forced the handoff-pressure rung at least
    # once — preempt spans carry the routing reason
    preempts = [r for rid in rids for r in _spans(records, rid, "preempt")]
    assert preempts and all(
        p["reason"] == "handoff-pressure" for p in preempts
    )
    assert router.metrics()["preempt_routes"] >= 1


def test_shed_decision_closes_root_immediately():
    tracer = ReqTracer()
    trace_decision(tracer, 5, Decision(SHED, -1, "queue_depth"),
                   session=3, prompt_len=16)
    assert validate_trace(tracer.records) == []
    end = next(r for r in tracer.records if r.get("ev") == "end")
    assert end["outcome"] == "shed" and end["reason"] == "queue_depth"
    gate = next(r for r in tracer.records if r.get("name") == "gate")
    assert gate["action"] == "shed"


def test_logical_clock_is_strictly_monotone_across_threads():
    tracer = ReqTracer()
    n, per = 8, 50

    def worker(rid):
        root = tracer.open_root(rid)
        for i in range(per):
            tracer.event(rid, f"e{i}", parent=root)
        tracer.end(root)

    threads = [threading.Thread(target=worker, args=(rid,))
               for rid in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    seqs = [r["seq"] for r in tracer.records]
    assert sorted(seqs) == list(range(n * (per + 2)))
    assert validate_trace(tracer.records) == []


def test_null_tracer_is_inert():
    assert NULL_REQTRACER.begin(1, "x") == 0
    assert NULL_REQTRACER.open_root(1) == 0
    NULL_REQTRACER.end(0)
    NULL_REQTRACER.event(1, "x")
    NULL_REQTRACER.link(1, 0, 0)
    assert NULL_REQTRACER.records == []


def test_reserved_attr_keys_are_rejected():
    tracer = ReqTracer()
    with pytest.raises(ValueError, match="reserved"):
        tracer.begin(1, "x", seq=3)


def test_validator_catches_unclosed_orphaned_and_multiroot():
    tracer = ReqTracer()
    root = tracer.open_root(1)
    child = tracer.begin(1, "phase")
    tracer.end(child)
    tracer.end(root)
    records = list(tracer.records)
    assert validate_trace(records) == []
    # drop the child's end: unclosed
    broken = [r for r in records
              if not (r.get("ev") == "end" and r["span"] == child)]
    assert any("never closed" in e for e in validate_trace(broken))
    # orphan parent: a span naming a parent never opened in this trace
    orphan = records + [{
        "kind": "span", "v": 1, "ev": "begin", "trace": 1, "span": 99,
        "parent": 42, "name": "ghost", "seq": 100, "t": 0.0,
    }]
    errs = validate_trace(orphan)
    assert any("parent 42" in e for e in errs)
    assert any("never closed" in e for e in errs)  # the ghost itself
    # second root
    two_roots = records + [{
        "kind": "span", "v": 1, "ev": "begin", "trace": 1, "span": 100,
        "name": "request", "seq": 101, "t": 0.0,
    }, {
        "kind": "span", "v": 1, "ev": "end", "trace": 1, "span": 100,
        "seq": 102, "t": 0.0, "dur_s": 0.0,
    }]
    assert any("exactly one root" in e for e in validate_trace(two_roots))


# ---------------------------------------------------------------------------
# exporters and CLIs
# ---------------------------------------------------------------------------


def test_chrome_trace_export_tracks_and_flow_arrows(disagg_run):
    records, _file_records, rids, _router = disagg_run
    trace = chrome_trace(records)
    events = trace["traceEvents"]
    xs = [e for e in events if e.get("ph") == "X"]
    assert xs and all(e["dur"] >= 0 for e in xs)
    # one process per request, thread rows per replica
    assert {e["pid"] for e in xs} == set(rids)
    names = {e["args"]["name"] for e in events
             if e.get("name") == "process_name"}
    assert names == {f"request {rid}" for rid in rids}
    flows = [e for e in events if e.get("ph") in ("s", "f")]
    assert len(flows) >= 2 * len(rids)  # one arrow pair per handoff
    json.dumps(trace)  # serializable as-is


def test_explain_request_cli_and_assert_complete(disagg_run, tmp_path,
                                                 capsys):
    explain_request = _import_script("explain_request")
    _records, file_records, rids, _router = disagg_run
    path = tmp_path / "run.jsonl"
    with open(path, "w") as f:
        for r in file_records:
            f.write(json.dumps(r) + "\n")
    rc = explain_request.main(
        [str(path), "--rid", str(rids[0]), "--assert-complete",
         "--perfetto", str(tmp_path / "out.trace.json")]
    )
    out = capsys.readouterr().out
    assert rc == 0
    assert "[complete]" in out and "handoff" in out
    assert "per-phase wall" in out
    assert json.load(open(tmp_path / "out.trace.json"))["traceEvents"]
    # --find predicates locate a handed-off rid without hard-coding
    rc = explain_request.main([str(path), "--find", "handed-off",
                              "--assert-complete"])
    assert rc == 0
    # a torn stream (one end record dropped) must FAIL the gate
    spans = [r for r in file_records if r.get("kind") == "span"]
    drop = next(r for r in spans
                if r.get("ev") == "end" and r["trace"] == rids[0])
    with open(path, "w") as f:
        for r in file_records:
            if r is not drop:
                f.write(json.dumps(r) + "\n")
    rc = explain_request.main([str(path), "--rid", str(rids[0]),
                               "--assert-complete"])
    assert rc == 2
    assert "INCOMPLETE" in capsys.readouterr().out


def test_pdt_top_renders_inflight_and_pressure_rows(disagg_run,
                                                    tmp_path, capsys):
    pdt_top = _import_script("pdt_top")
    _records, file_records, _rids, _router = disagg_run
    path = tmp_path / "run.jsonl"
    with open(path, "w") as f:
        for r in file_records:
            f.write(json.dumps(r) + "\n")
        # one still-open root: the in-flight gauge must count it
        f.write(json.dumps({
            "kind": "span", "v": 1, "ev": "begin", "trace": 999,
            "span": 100000, "name": "request", "seq": 100000, "t": 0.0,
        }) + "\n")
    assert pdt_top.main([str(path), "--once"]) == 0
    out = capsys.readouterr().out
    assert "inflight 1 requests" in out
    assert "pressure" in out and "swap" in out


def test_telemetry_report_require_spans(disagg_run, tmp_path):
    import subprocess
    import sys

    _records, file_records, _rids, _router = disagg_run
    path = tmp_path / "run.jsonl"
    with open(path, "w") as f:
        for r in file_records:
            f.write(json.dumps(r) + "\n")
    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts/telemetry_report.py"),
         str(path), "--json", "--require", "spans"],
        capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert res.returncode == 0, res.stdout + res.stderr
    assert "request traces" in res.stdout


# ---------------------------------------------------------------------------
# schema registry: replay every emitter, assert conformance
# ---------------------------------------------------------------------------


def test_every_emitter_conforms_to_schema_registry(disagg_run, model,
                                                   tmp_path):
    cfg, params = model
    _records, file_records, _rids, router = disagg_run
    # the fleet run covers request/span/preempt/swap/fleet_summary;
    # replay the remaining emitters into a fresh stream
    path = tmp_path / "extra.jsonl"
    with MetricsLogger(str(path)) as mlog:
        rep = router.replicas[1]
        mlog.log(kind="serving_summary", **rep.metrics())
        mlog.log(kind="goodput", **rep.goodput.report())
        sentinel = AnomalySentinel(threshold=4.0, metrics_log=mlog,
                                   min_samples=8)
        for _ in range(12):
            sentinel.observe("tick_time", 0.01)
        assert sentinel.observe("tick_time", 10.0) is not None
    with open(path) as f:
        extra = [json.loads(line) for line in f if line.strip()]
    kinds = {r.get("kind") for r in file_records} | {
        r.get("kind") for r in extra
    }
    assert {"request", "span", "preempt", "swap", "fleet_summary",
            "serving_summary", "goodput", "anomaly"} <= kinds
    errors = validate_stream(file_records + extra)
    assert errors == [], errors[:10]


def test_schema_registry_flags_drift():
    from pytorch_distributed_tpu.telemetry.schema import validate_record

    assert validate_record({"rid": 1}) == ["record has no 'kind' key"]
    errs = validate_record({"kind": "request", "rid": 1})
    assert any("replica_id" in e for e in errs)
    # span ev refinement
    errs = validate_record({"kind": "span", "v": 1, "ev": "begin",
                            "trace": 1, "span": 1, "seq": 0, "t": 0.0})
    assert errs == ["kind=span ev=begin: missing required key 'name'"]
    # unknown kinds pass unless strict
    assert validate_record({"kind": "experiment"}) == []
    assert validate_record({"kind": "experiment"}, strict=True)


# ---------------------------------------------------------------------------
# SpanTracer: per-thread stacks (satellite for ROADMAP item 3's threads)
# ---------------------------------------------------------------------------


def test_spantracer_per_thread_stacks_do_not_interleave():
    tracer = SpanTracer(mirror_jax=False)
    barrier = threading.Barrier(2)
    errors = []

    def worker(name):
        try:
            with tracer.span(f"outer_{name}"):
                barrier.wait(timeout=5)  # both outers open concurrently
                assert tracer.stack() == [f"outer_{name}"]
                with tracer.span(f"inner_{name}"):
                    barrier.wait(timeout=5)
                    assert tracer.stack() == [
                        f"outer_{name}", f"inner_{name}"
                    ]
        except Exception as e:  # surfaced below; a thread must not die mute
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(n,))
               for n in ("a", "b")]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert errors == []
    assert tracer.stack() == []  # main thread never opened a span
    events = {e["name"]: e for e in tracer.events()}
    assert len(events) == 4
    for name in ("a", "b"):
        inner = events[f"inner_{name}"]
        # each inner's parent comes from ITS OWN thread's stack
        assert inner["args"]["parent"] == f"outer_{name}"
        assert inner["args"]["depth"] == 1
        assert "args" not in events[f"outer_{name}"] or \
            "parent" not in events[f"outer_{name}"].get("args", {})


def test_rules_threads_passes_telemetry_modules_clean():
    ctx = LintContext(modules=[], mesh_axes=set(), axis_constants={})
    for rel in ("pytorch_distributed_tpu/telemetry/spans.py",
                "pytorch_distributed_tpu/telemetry/reqtrace.py",
                "pytorch_distributed_tpu/telemetry/schema.py"):
        mod = parse_file(os.path.join(REPO, rel), REPO)
        findings = check_threads(mod, ctx)
        assert findings == [], [f.render() for f in findings]


# ---------------------------------------------------------------------------
# /metrics exporter under concurrent scrapes during span emission
# ---------------------------------------------------------------------------


def test_metrics_exporter_concurrent_scrapes_no_torn_lines():
    tracer = ReqTracer()
    state = {"ticks": 0}

    def collect():
        # a collect() racing the emitting loop, as a live fleet's would
        return {"ticks": state["ticks"],
                "open_spans": len(tracer.open_spans()),
                "inflight": len(tracer.open_traces())}

    stop = threading.Event()
    results = {}

    def scraper(i):
        seen = []
        while not stop.is_set():
            with urllib.request.urlopen(
                f"http://127.0.0.1:{exporter.port}/metrics", timeout=5
            ) as resp:
                body = resp.read().decode()
            for line in body.strip().splitlines():
                # no torn lines: every line is a comment or "name value"
                if line.startswith("#"):
                    assert line.startswith("# TYPE pdt_"), line
                    continue
                name, value = line.split(" ")
                assert name.startswith("pdt_")
                float(value)
            seen.append(
                float(next(ln.split(" ")[1]
                           for ln in body.splitlines()
                           if ln.startswith("pdt_ticks "))))
        results[i] = seen

    with MetricsExporter(collect, port=0) as exporter:
        scrapers = [threading.Thread(target=scraper, args=(i,))
                    for i in range(3)]
        for t in scrapers:
            t.start()
        for tick in range(200):  # emit spans while scrapes are in flight
            state["ticks"] = tick + 1
            rid = tick % 7
            root = tracer.open_root(rid)
            span = tracer.begin(rid, "phase", parent=root)
            tracer.event(rid, "tick", parent=span, i=tick)
            tracer.end(span)
        stop.set()
        for t in scrapers:
            t.join()
    for seen in results.values():
        assert seen, "scraper never completed a scrape"
        # the counter is monotone across one scraper's sequential reads
        assert all(b >= a for a, b in zip(seen, seen[1:])), seen
