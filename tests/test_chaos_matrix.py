"""Chaos matrix (round 19 tentpole): replica-failure tolerance.

Fault × request-state grid over the serve-side fault sites
(``serve.dispatch`` / ``serve.collect`` / ``serve.handoff_export`` /
``serve.handoff_import``; kinds ``raise`` and ``hang``): whatever dies,
every submitted request must FINISH (token-identical to a fault-free
run), SHED with ``outcome="failed"`` (attempt cap), or EXPIRE with
``outcome="deadline"`` — never hang. Each scenario also proves the
teardown leak-free (blocksan shadow ledger, zero violations) and the
request traces closed (``validate_trace`` empty). The fast subset here
is tier-1; the full grid is ``@slow``. Deadline enforcement gets its own
state matrix: a request whose deadline lapses while queued, mid-prefill,
decoding, parked, mid-swap, or handoff-ready must expire through the
round-18 cancel path with ``outcome="deadline"``.
"""

import json
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_tpu.models.transformer import (
    TransformerLM,
    tiny_config,
)
from pytorch_distributed_tpu.resilience import faults
from pytorch_distributed_tpu.resilience.faults import FaultPlan, FaultSpec
from pytorch_distributed_tpu.serving import Scheduler
from pytorch_distributed_tpu.telemetry.flightrec import FlightRecorder
from pytorch_distributed_tpu.telemetry.reqtrace import (
    ReqTracer,
    validate_trace,
)


@pytest.fixture(scope="module")
def model():
    cfg = tiny_config(attention="dense", max_seq_len=96)
    params = TransformerLM(cfg).init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return cfg, params


def _prompts(cfg, n=3, base=9, seed=0):
    rng = np.random.default_rng(seed)
    return [
        rng.integers(1, cfg.vocab_size, (base + i,)).astype(np.int32)
        for i in range(n)
    ]


def _fleet(cfg, params, monkeypatch, **over):
    """A blocksan-armed FleetRouter with an in-memory request tracer —
    every chaos scenario runs under both proof layers."""
    from pytorch_distributed_tpu.fleet import FleetRouter

    monkeypatch.setenv("PDT_BLOCKSAN", "1")
    kw = dict(n_replicas=2, n_slots=3, block_len=8, prefill_chunk=8,
              reqtrace=ReqTracer(), flightrec=FlightRecorder())
    kw.update(over)
    return FleetRouter(cfg, params, **kw)


def _assert_proofs(router):
    """The per-scenario gate: zero leaked blocks and closed span trees."""
    router.blocksan.assert_clean()
    assert validate_trace(router.reqtrace.records) == []


def _run(router, prompts, max_new=6, plan=None, deadline_s=None,
         max_steps=4000):
    if plan is not None:
        faults.install_plan(plan)
    try:
        rids = [router.submit(p, max_new, deadline_s=deadline_s)
                for p in prompts]
        out = router.drain(max_steps=max_steps)
    finally:
        if plan is not None:
            faults.clear_plan()
    return rids, out


# ---------------------------------------------------------------------------
# fast subset (tier-1): one kill per fault class + the core guarantees
# ---------------------------------------------------------------------------


def test_redispatch_streams_identical_to_fault_free(model, monkeypatch):
    """THE recovery gate: kill a replica mid-flight; every request's
    greedy stream must be token-identical to the fault-free run — the
    replay re-prefills original prompt + delivered tokens, so clients
    observe append-only streams with no divergence."""
    cfg, params = model
    prompts = _prompts(cfg)
    ref_router = _fleet(cfg, params, monkeypatch)
    ref_rids, ref_out = _run(ref_router, prompts)
    ref = {rid: ref_out[rid] for rid in ref_rids}
    assert all(len(v) == 6 for v in ref.values())
    _assert_proofs(ref_router)

    router = _fleet(cfg, params, monkeypatch, fail_threshold=1)
    plan = FaultPlan([
        FaultSpec(site="serve.dispatch", kind="raise", at=2, times=1)
    ])
    rids, out = _run(router, prompts, plan=plan)
    assert plan.fired == [("serve.dispatch", 2, "raise")]
    m = router.metrics()
    assert m["replica_deaths"] == 1 and m["replicas_healthy"] == 1
    assert m["redispatched"] >= 1 and m["failed"] == 0
    assert {rid: out[rid] for rid in rids} == ref
    assert "dead" in [h["state"] for h in router.health]
    _assert_proofs(router)
    # the health transitions are flight-recorder facts, not just state
    deaths = [r for r in router.flightrec.snapshot()
              if r.get("kind") == "health" and r.get("state") == "dead"]
    assert len(deaths) == 1


@pytest.mark.parametrize("site,n_replicas", [
    ("serve.collect", 2),
    ("serve.handoff_export", 2),
    ("serve.handoff_import", 3),
])
def test_transient_fault_marks_suspect_then_recovers(
        model, monkeypatch, site, n_replicas):
    """One injected failure below ``fail_threshold``: the replica goes
    suspect, the next clean touch clears it, and every request still
    finishes — a single blip is a warning, not a death sentence."""
    cfg, params = model
    disagg = site.startswith("serve.handoff")
    router = _fleet(cfg, params, monkeypatch, n_replicas=n_replicas,
                    disaggregate=disagg, fail_threshold=2)
    plan = FaultPlan([FaultSpec(site=site, kind="raise", at=0, times=1)])
    rids, out = _run(router, _prompts(cfg), max_new=4, plan=plan)
    assert plan.fired
    assert all(len(out[rid]) == 4 for rid in rids)
    assert all(h["state"] == "healthy" for h in router.health)
    assert sum(h["failures"] for h in router.health) == 1
    assert router.metrics()["replica_deaths"] == 0
    _assert_proofs(router)


@pytest.mark.slow
def test_hang_overrunning_tick_deadline_condemns(model, monkeypatch):
    """The hang kind: the tick returns late instead of raising — the
    tick deadline must condemn the replica exactly like a crash, and
    the fleet recovers identically. Warmed first: the deadline
    presumes compiled replicas (a compile IS a legitimate stall)."""
    cfg, params = model
    router = _fleet(cfg, params, monkeypatch, tick_deadline_s=0.25)
    router.warmup()
    plan = FaultPlan([
        FaultSpec(site="serve.dispatch", kind="hang", at=2, times=1,
                  seconds=0.3)
    ])
    rids, out = _run(router, _prompts(cfg), plan=plan)
    assert plan.fired == [("serve.dispatch", 2, "hang")]
    states = [h["state"] for h in router.health]
    assert states.count("dead") == 1
    assert all(len(out[rid]) == 6 for rid in rids)
    assert any(
        str(r.get("reason", "")).startswith("tick-hang")
        for r in router.flightrec.snapshot()
        if r.get("kind") == "health"
    ), "condemnation reason should name the hang"
    _assert_proofs(router)


@pytest.mark.slow
def test_attempt_cap_sheds_with_outcome_failed(model, monkeypatch):
    """Serial replica deaths exhaust the re-dispatch budget: a request
    harvested TWICE sheds with ``outcome="failed"`` (root span closes
    with that outcome) instead of retrying forever, while a request
    harvested only once keeps WAITING through the fleet-wide outage —
    a later revive still delivers its full stream."""
    cfg, params = model
    router = _fleet(cfg, params, monkeypatch, fail_threshold=1,
                    redispatch_max_attempts=1,
                    redispatch_base_delay_s=0.0)
    # idx 2 is r0's tick-1 dispatch (kills r0); the survivors replay
    # onto r1, and idx 6 — r1's third solo tick — kills it too
    plan = FaultPlan([
        FaultSpec(site="serve.dispatch", kind="raise", at=2, times=1),
        FaultSpec(site="serve.dispatch", kind="raise", at=6, times=1),
    ])
    faults.install_plan(plan)
    try:
        rids = [router.submit(p, 6) for p in _prompts(cfg)]
        for _ in range(12):
            router.step()
    finally:
        faults.clear_plan()
    assert len(plan.fired) == 2
    m = router.metrics()
    assert m["replica_deaths"] == 2 and m["replicas_healthy"] == 0
    assert m["failed"] >= 1
    assert set(router.failed) <= set(rids)
    roots = [r for r in router.reqtrace.records
             if r.get("ev") == "end" and r.get("outcome") == "failed"]
    assert len(roots) == len(router.failed)
    # requests with attempts left are held, not dropped: the whole
    # fleet is dead, so they wait for a revive
    waiting = sorted(e["rid"] for e in router._pending_redispatch)
    assert set(waiting) == set(rids) - set(router.failed)
    assert not router.idle
    router.revive(0)
    out = router.drain(max_steps=4000)
    for rid in waiting:
        assert len(out[rid]) == 6
    _assert_proofs(router)


def test_kill_with_parked_and_midswap_requests(model, monkeypatch):
    """The hard harvest states: the dying replica holds a PARKED
    (swapped-out) request and one MID-SWAP (open swap window). Abandon
    must close the window without committing, free every chain, and
    the replay must still deliver full streams."""
    cfg, params = model
    router = _fleet(cfg, params, monkeypatch, fail_threshold=1,
                    offload=True, swap_policy="swap", protect_ticks=0)
    prompts = _prompts(cfg, n=2)
    rids = [router.submit(p, 8) for p in prompts]
    for _ in range(3):
        router.step()
    victim_replica = router.placement[rids[0]]
    s = router.replicas[victim_replica]
    assert s.preempt(rids[0], reason="chaos").choice == "swap"
    # rids[0] now sits in the open swap window (_swapping) — kill the
    # victim replica BEFORE its next dispatch tick finalizes the swap
    # (serve.dispatch fires before any tick work, so the harvest sees
    # the window open). The step order is the alive fleet order, so
    # the victim's dispatch index within the next step is its position
    # in that order.
    order = router._alive(router.decode_group + router.entry_group)
    plan = FaultPlan([
        FaultSpec(site="serve.dispatch", kind="raise",
                  at=order.index(victim_replica), times=1)
    ])
    faults.install_plan(plan)
    try:
        router.step()
    finally:
        faults.clear_plan()
    assert plan.fired
    assert router.health[victim_replica]["state"] == "dead"
    out = router.drain(max_steps=4000)
    assert all(len(out[rid]) == 8 for rid in rids), {
        k: len(out.get(k, ())) for k in rids
    }
    _assert_proofs(router)


@pytest.mark.slow
def test_revive_behind_warmup_no_recompiles_no_drops(model, monkeypatch):
    """Degraded operation + recovery: kill a replica, serve degraded,
    then revive it behind compile-cache warmup — survivors never
    recompile (program-name fingerprint), the rejoined replica takes
    traffic, and no request drops across the whole episode."""
    cfg, params = model
    router = _fleet(cfg, params, monkeypatch, fail_threshold=1)
    plan = FaultPlan([
        FaultSpec(site="serve.dispatch", kind="raise", at=2, times=1)
    ])
    rids, out = _run(router, _prompts(cfg), plan=plan)
    assert all(len(out[rid]) == 6 for rid in rids)
    dead = [i for i, h in enumerate(router.health)
            if h["state"] == "dead"]
    assert len(dead) == 1
    fingerprints = {
        i: tuple(s.engine.compiled_program_names())
        for i, s in enumerate(router.replicas) if i not in dead
    }
    router.revive(dead[0], warmup=True)
    assert router.health[dead[0]]["state"] == "healthy"
    rid2 = router.submit(_prompts(cfg, n=1, base=12)[0], 4)
    out2 = router.drain(max_steps=2000)
    assert len(out2[rid2]) == 4
    for i, fp in fingerprints.items():
        assert tuple(
            router.replicas[i].engine.compiled_program_names()
        ) == fp, f"survivor r{i} recompiled across the revive"
    router.assert_registry_covers()
    _assert_proofs(router)


@pytest.mark.slow
def test_prefill_death_waits_for_revive_in_disagg(model, monkeypatch):
    """Disaggregated fleet with ONE prefill replica: its death leaves
    no entry survivor, so harvested requests WAIT (the fleet is
    explicitly not idle) and a revive drains them — no silent drop,
    no bogus completion."""
    cfg, params = model
    router = _fleet(cfg, params, monkeypatch, disaggregate=True,
                    fail_threshold=1)
    # entry ticks are the odd site indices (decode group ticks first)
    plan = FaultPlan([
        FaultSpec(site="serve.dispatch", kind="raise", at=3, times=1)
    ])
    faults.install_plan(plan)
    try:
        rids = [router.submit(p, 4) for p in _prompts(cfg)]
        for _ in range(8):
            router.step()
    finally:
        faults.clear_plan()
    assert router.health[0]["state"] == "dead"
    assert not router.idle  # pending re-dispatch IS in-flight work
    assert router.metrics()["redispatch_pending"] >= 1
    # a fresh submit while no entry replica is alive sheds explicitly
    shed_rid = router.submit(_prompts(cfg, n=1)[0], 4)
    assert router.rejected[shed_rid] == "fleet-unavailable"
    router.revive(0)
    out = router.drain(max_steps=4000)
    assert all(len(out[rid]) == 4 for rid in rids)
    _assert_proofs(router)


# ---------------------------------------------------------------------------
# deadline enforcement: expiry in every request state
# ---------------------------------------------------------------------------


def _deadline_scheduler(cfg, params, **over):
    from pytorch_distributed_tpu.analysis.blocksan import BlockSanitizer

    kw = dict(n_slots=2, block_len=8, prefill_chunk=8, offload=True,
              swap_policy="swap", protect_ticks=0,
              blocksan=BlockSanitizer(), reqtrace=ReqTracer())
    kw.update(over)
    return Scheduler(cfg, params, **kw)


def _expire_here(s, rid, state_key):
    """Assert rid currently sits in ``state_key``, then force its
    deadline into the past and tick once — it must expire with
    outcome=deadline. The expiry is forced through the live ``Request``
    record (``harvest_requests`` is a read-only traversal of every
    bucket) rather than by sleeping: the first ticks JIT-compile, so a
    wall-clock budget would race the compiler."""
    assert rid in s.stuck_rids().get(state_key, []), (
        state_key, s.stuck_rids()
    )
    before = s.metrics()["deadline_misses"]
    req = next(r for r in s.harvest_requests() if r.rid == rid)
    req.deadline = 0.0  # perf_counter epoch: unambiguously lapsed
    s.step()
    assert s.metrics()["deadline_misses"] == before + 1
    ends = [r for r in s.reqtrace.records
            if r.get("ev") == "end" and r.get("outcome") == "deadline"]
    assert ends, "no span closed with outcome=deadline"


def test_deadline_expires_queued(model):
    cfg, params = model
    s = _deadline_scheduler(cfg, params, n_slots=1)
    a = s.submit(np.arange(1, 9, dtype=np.int32), 64)
    b = s.submit(np.arange(2, 12, dtype=np.int32), 4, deadline_s=30.0)
    s.step()
    _expire_here(s, b, "queued")
    s.cancel(a)
    s.drain()
    assert s._san.verify_quiesce() == []
    assert validate_trace(s.reqtrace.records) == []


def test_deadline_expires_mid_prefill(model):
    cfg, params = model
    s = _deadline_scheduler(cfg, params)
    # 3 chunks of prefill; expire after the first
    rid = s.submit(np.arange(1, 21, dtype=np.int32), 4, deadline_s=30.0)
    s.step()
    _expire_here(s, rid, "prefill")
    s.drain()
    assert s._san.verify_quiesce() == []
    assert validate_trace(s.reqtrace.records) == []


def test_deadline_expires_decoding(model):
    cfg, params = model
    s = _deadline_scheduler(cfg, params)
    rid = s.submit(np.arange(1, 9, dtype=np.int32), 64, deadline_s=30.0)
    for _ in range(3):
        s.step()
    _expire_here(s, rid, "decoding")
    s.drain()
    assert s._san.verify_quiesce() == []
    assert validate_trace(s.reqtrace.records) == []


def test_deadline_expires_parked(model):
    cfg, params = model
    s = _deadline_scheduler(cfg, params)
    rid = s.submit(np.arange(1, 9, dtype=np.int32), 64, deadline_s=30.0)
    for _ in range(3):
        s.step()
    assert s.preempt(rid, reason="test").choice == "swap"
    # hold it parked across ticks: every restore attempt aborts at the
    # h2d hazard (host copy intact, retried), so the free slot cannot
    # pull the request back to decoding before the deadline sweep sees
    # it in the parked state
    faults.install_plan(FaultPlan([
        FaultSpec(site="kv.swap_in_h2d", kind="raise", at=0, times=64)
    ]))
    try:
        s.step()  # finalizes the swap-out; the restore aborts → parked
        _expire_here(s, rid, "parked")
    finally:
        faults.clear_plan()
    s.drain()
    assert s._san.verify_quiesce() == []
    assert validate_trace(s.reqtrace.records) == []


def test_deadline_expires_mid_swap(model):
    cfg, params = model
    s = _deadline_scheduler(cfg, params)
    rid = s.submit(np.arange(1, 9, dtype=np.int32), 64, deadline_s=30.0)
    for _ in range(3):
        s.step()
    assert s.preempt(rid, reason="test").choice == "swap"
    # the swap window is OPEN (not yet finalized by the next tick)
    _expire_here(s, rid, "swapping")
    s.drain()
    assert s._san.verify_quiesce() == []
    assert validate_trace(s.reqtrace.records) == []


def test_deadline_expires_handoff_ready(model):
    cfg, params = model
    s = _deadline_scheduler(cfg, params, prefill_only=True, handoff=True,
                            offload=False, swap_policy="recompute")
    rid = s.submit(np.arange(1, 9, dtype=np.int32), 4, deadline_s=30.0)
    for _ in range(3):
        s.step()
    _expire_here(s, rid, "handoff-ready")
    s.drain()
    assert s._san.verify_quiesce() == []
    assert validate_trace(s.reqtrace.records) == []


def test_deadline_sheds_at_admission(model, monkeypatch):
    """Admission is the FIRST enforcement point: an already-expired
    budget never touches a replica; the root closes outcome=deadline
    and the router counts a deadline shed, not a generic one."""
    cfg, params = model
    router = _fleet(cfg, params, monkeypatch)
    rid = router.submit(_prompts(cfg, n=1)[0], 4, deadline_s=-0.01)
    assert router.rejected[rid] == "deadline-expired"
    m = router.metrics()
    assert m["deadline_sheds"] == 1 and m["shed"] == 1
    router.drain()
    ends = [r for r in router.reqtrace.records
            if r.get("ev") == "end" and r.get("outcome") == "deadline"]
    assert len(ends) == 1
    _assert_proofs(router)


@pytest.mark.slow
def test_deadline_survives_redispatch_unchanged(model, monkeypatch):
    """Replica loss must not grant a fresh latency budget: the absolute
    deadline travels with the replay, and a harvested request whose
    deadline lapses while waiting expires with outcome=deadline."""
    cfg, params = model
    router = _fleet(cfg, params, monkeypatch, disaggregate=True,
                    fail_threshold=1, redispatch_base_delay_s=0.01)
    router.warmup()  # ticks must be compile-free for a sub-second SLO
    plan = FaultPlan([
        FaultSpec(site="serve.dispatch", kind="raise", at=3, times=1)
    ])
    faults.install_plan(plan)
    try:
        rids = [router.submit(p, 4, deadline_s=0.5)
                for p in _prompts(cfg, n=2)]
        for _ in range(6):
            router.step()
    finally:
        faults.clear_plan()
    assert router.health[0]["state"] == "dead"
    assert router.metrics()["redispatch_pending"] >= 1
    # no entry survivor: the deadline keeps ticking while they wait
    time.sleep(0.55)
    router.step()
    m = router.metrics()
    assert m["deadline_expired_redispatch"] >= 1
    assert router.idle  # expired entries leave no pending work behind
    for rid in rids:
        assert len(router.results.get(rid, ())) < 4
    _assert_proofs(router)


# ---------------------------------------------------------------------------
# drain diagnostics + health telemetry (satellites)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_drain_names_stuck_rids(model, monkeypatch):
    cfg, params = model
    s = _deadline_scheduler(cfg, params, n_slots=1)
    a = s.submit(np.arange(1, 9, dtype=np.int32), 4)
    b = s.submit(np.arange(1, 12, dtype=np.int32), 4)
    s.step()
    with pytest.raises(RuntimeError) as exc:
        s.drain(max_steps=0)
    assert "stuck rids by state" in str(exc.value)
    assert str(b) in str(exc.value)
    s.drain()

    router = _fleet(cfg, params, monkeypatch, disaggregate=True,
                    fail_threshold=1)
    plan = FaultPlan([
        FaultSpec(site="serve.dispatch", kind="raise", at=3, times=1)
    ])
    faults.install_plan(plan)
    try:
        rid = router.submit(_prompts(cfg, n=1)[0], 4)
        for _ in range(6):
            router.step()
        with pytest.raises(RuntimeError) as exc:
            router.drain(max_steps=3)
    finally:
        faults.clear_plan()
    assert "awaiting redispatch" in str(exc.value)
    assert str(rid) in str(exc.value)
    router.revive(0)
    router.drain()


def test_health_and_redispatch_jsonl_schema(model, tmp_path, monkeypatch):
    """kind="health" records and the failure-plane fleet_summary keys
    stream schema-valid JSONL — the telemetry contract (satellite 6)."""
    from pytorch_distributed_tpu.telemetry.schema import validate_stream
    from pytorch_distributed_tpu.utils.profiling import MetricsLogger

    cfg, params = model
    path = tmp_path / "chaos.jsonl"
    mlog = MetricsLogger(str(path))
    monkeypatch.setenv("PDT_BLOCKSAN", "1")
    from pytorch_distributed_tpu.fleet import FleetRouter

    router = FleetRouter(cfg, params, n_replicas=2, n_slots=3,
                         block_len=8, prefill_chunk=8, fail_threshold=1,
                         metrics_log=mlog)
    plan = FaultPlan([
        FaultSpec(site="serve.dispatch", kind="raise", at=2, times=1)
    ])
    rids, out = _run(router, _prompts(cfg), plan=plan)
    assert all(len(out[rid]) == 6 for rid in rids)
    router.log_summary()
    mlog.close()
    records = [json.loads(l) for l in path.read_text().splitlines() if l]
    assert validate_stream(records) == []
    health = [r for r in records if r.get("kind") == "health"]
    states = [r["state"] for r in health]
    # the full condemnation arc is on the wire: draining then dead
    assert "draining" in states and "dead" in states
    fleet = [r for r in records if r.get("kind") == "fleet_summary"][-1]
    assert fleet["replica_deaths"] == 1
    assert fleet["redispatched"] >= 1
    assert fleet["deadline_misses"] == 0
    assert fleet["replicas_healthy"] == 1
    assert fleet["r0_health"] in ("dead", "healthy")
    router.blocksan.assert_clean()


# ---------------------------------------------------------------------------
# the full grid (@slow): every serve site × phase, raise + hang kinds
# ---------------------------------------------------------------------------


_GRID_SITES = [
    ("serve.dispatch", False),
    ("serve.collect", False),
    ("serve.handoff_export", True),
    ("serve.handoff_import", True),
]


@pytest.mark.slow
@pytest.mark.parametrize("at", [0, 3, 7], ids=["early", "mid", "late"])
@pytest.mark.parametrize(
    "site,disagg", _GRID_SITES, ids=[s.split(".")[1] for s, _ in _GRID_SITES]
)
def test_chaos_grid_raise(model, monkeypatch, site, disagg, at):
    """The full raise grid: a replica death at every serve fault site,
    injected early (queued/prefill), mid (decoding), and late — every
    request finishes or sheds, never hangs; ledger clean; traces
    closed. Survivor-less episodes revive and still finish."""
    cfg, params = model
    router = _fleet(cfg, params, monkeypatch,
                    n_replicas=3 if disagg else 2,
                    disaggregate=disagg, fail_threshold=1,
                    redispatch_base_delay_s=0.005)
    plan = FaultPlan([FaultSpec(site=site, kind="raise", at=at, times=1)])
    faults.install_plan(plan)
    try:
        rids = [router.submit(p, 5) for p in _prompts(cfg, n=4)]
        for _ in range(64):
            router.step()
            if router.idle:
                break
        if not router.idle and not router._alive(router.entry_group):
            for i, h in enumerate(router.health):
                if h["state"] == "dead":
                    router.revive(i, warmup=False)
        out = router.drain(max_steps=4000)
    finally:
        faults.clear_plan()
    delivered = {rid: len(out.get(rid, ())) for rid in rids}
    finished = {rid for rid, n in delivered.items() if n == 5}
    shed = set(router.failed) | set(router.rejected)
    assert finished | shed == set(rids), (delivered, router.failed)
    _assert_proofs(router)


@pytest.mark.slow
@pytest.mark.parametrize("site", ["serve.dispatch", "serve.collect"])
def test_chaos_grid_hang(model, monkeypatch, site):
    """The hang half of the grid: a wedged tick at each loop-side site
    condemns via the tick deadline; recovery then matches the raise
    path bit for bit."""
    cfg, params = model
    router = _fleet(cfg, params, monkeypatch, tick_deadline_s=0.25,
                    redispatch_base_delay_s=0.005)
    router.warmup()
    plan = FaultPlan([
        FaultSpec(site=site, kind="hang", at=2, times=1, seconds=0.3)
    ])
    rids, out = _run(router, _prompts(cfg, n=4), max_new=5, plan=plan)
    assert plan.fired
    assert [h["state"] for h in router.health].count("dead") == 1
    assert all(len(out[rid]) == 5 for rid in rids)
    _assert_proofs(router)
