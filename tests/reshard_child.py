"""Cross-topology kill-matrix child: a tiny real LM training run whose
mesh shape is a command-line parameter.

The elastic-resume proof (tests/test_reshard.py, ROADMAP item 4) runs
this child three ways against ONE save directory: killed by an injected
SIGKILL on mesh (4,1,2), then relaunched on (2,1,2) and (8,1,1) — the
relaunch must reshard the checkpoint onto its own topology and finish
the run. The GLOBAL batch is fixed by ``--global-batch`` (the per-replica
batch is derived from the mesh's data-axis size), and the LM carries no
batch-norm and no dropout, so the training FUNCTION is identical across
topologies — the logged loss series of a resumed run matches an
unpreempted control up to cross-topology reduction order (bit-equal when
the topology is unchanged; see ANALYSIS.md "Elastic topology & reshard"
for the bit-stability boundary).

Every step appends (pid, gstep, loss) to ``progress.jsonl``;
``result.json`` lands on a clean finish. Not a pytest module — invoke as
``python tests/reshard_child.py --save-dir DIR --mesh 4,1,2``.
"""

import argparse
import json
import os
import sys

# 8 virtual CPU devices, pinned BEFORE jax import (same as conftest.py)
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--save-dir", required=True)
    ap.add_argument("--mesh", default="4,1,2",
                    help="data,seq,model axis sizes; model>1 runs TP")
    ap.add_argument("--global-batch", type=int, default=8,
                    help="fixed across topologies (per-replica bs is "
                    "global/data)")
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--steps-per-epoch", type=int, default=3)
    ap.add_argument("--fsdp", action="store_true",
                    help="ZeRO-shard the replicated leaves over data")
    args = ap.parse_args()
    dp, sp, mp = (int(x) for x in args.mesh.split(","))
    if args.global_batch % dp:
        raise SystemExit(
            f"--global-batch {args.global_batch} not divisible by "
            f"data={dp}"
        )

    from pytorch_distributed_tpu.data.tokens import SyntheticTokens
    from pytorch_distributed_tpu.models.transformer import tiny_config
    from pytorch_distributed_tpu.parallel import make_mesh
    from pytorch_distributed_tpu.train import LMTrainer, LMTrainerConfig

    progress_path = os.path.join(args.save_dir, "progress.jsonl")

    class LoggingTrainer(LMTrainer):
        """Appends (run pid, global step, loss) after every train step so
        the parent can compare series across crash + topology change."""

        def _post_step(self, metrics):
            super()._post_step(metrics)
            with open(progress_path, "a") as f:
                f.write(json.dumps({
                    "pid": os.getpid(),
                    "gstep": int(np.asarray(jax.device_get(self.state.step))),
                    "loss": float(jax.device_get(metrics["loss"])),
                }) + "\n")

    mesh = make_mesh(jax.devices()[: dp * sp * mp], data_parallel=dp,
                     seq_parallel=sp, model_parallel=mp)
    model_cfg = tiny_config(
        attention="dense",
        model_axis="model" if mp > 1 else None,
        tp_size=mp,
        dropout=0.0,  # no rng in the step: the function is topology-pure
    )
    cfg = LMTrainerConfig(
        epochs=args.epochs,
        batch_size=args.global_batch // dp,
        lr=1e-2,
        save_dir=args.save_dir,
        log_every=0,
        num_workers=0,
        prefetch=1,
        seed=0,
        save_every_n_steps=1,  # every step is a durability point
        keep_last_ckpts=4,
        fsdp=args.fsdp,
    )
    train = SyntheticTokens(
        size=args.global_batch * args.steps_per_epoch, seq_len=32,
        vocab_size=128,
    )
    val = SyntheticTokens(size=args.global_batch, seq_len=32,
                          vocab_size=128, seed=9)
    trainer = LoggingTrainer(model_cfg, train, val, cfg, mesh=mesh)
    resumed = trainer.try_resume()  # fit() re-runs this; it's idempotent
    start_epoch, start_step = trainer.start_epoch, trainer.start_step
    summary = trainer.fit()
    with open(os.path.join(args.save_dir, "result.json"), "w") as f:
        json.dump({
            "resumed": bool(resumed),
            "start_epoch": start_epoch,
            "start_step": start_step,
            "final_step": int(np.asarray(jax.device_get(trainer.state.step))),
            "val_loss": float(summary["loss"]),
            "mesh": [dp, sp, mp],
        }, f)
    return 0


if __name__ == "__main__":
    sys.exit(main())
