"""Step-interval checkpointing: save_every_n_steps + keep-last-K retention
(VERDICT r4 next #6). The reference saves only on suspend and on val
improvement (restnet_ddp.py:37-45,145-150) — these tests cover the added
durability policy: non-blocking step-<global_step>.ckpt saves, retention
that can never delete the only complete checkpoint, and resume picking
the newest restorable checkpoint (interval or suspend)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow

from pytorch_distributed_tpu.utils.checkpoint import (  # noqa: E402
    MANIFEST,
    Checkpointer,
    peek_leaf,
)
from conftest import FireAtStep, assert_trees_equal  # noqa: E402


def _payload(step):
    return {
        "state": {"step": jnp.asarray(step, jnp.int32),
                  "w": jnp.full((4, 4), float(step))},
        "epoch": 0, "step": step,
    }


def _step_dirs(d):
    return sorted(
        n for n in os.listdir(d)
        if n.startswith("step-") and n.endswith(".ckpt")
    )


def test_retention_keeps_last_k(tmp_path):
    d = str(tmp_path)
    ck = Checkpointer(d)
    for s in range(1, 6):
        ck.save_step_sharded(_payload(s), s, keep_last=2, block=False)
    ck.wait()
    assert _step_dirs(d) == ["step-00000004.ckpt", "step-00000005.ckpt"]
    # the kept ones are complete and restorable
    for n in _step_dirs(d):
        assert os.path.exists(os.path.join(d, n, MANIFEST))
    assert int(np.asarray(
        peek_leaf(os.path.join(d, "step-00000005.ckpt"), "state/step")
    )) == 5


def test_retention_never_deletes_only_complete(tmp_path):
    d = str(tmp_path)
    ck = Checkpointer(d)
    ck.save_step_sharded(_payload(1), 1, keep_last=1, block=True)
    # a NEWER but incomplete dir (crash mid-save: no manifest) must not
    # count as kept and must not displace the only complete checkpoint
    os.makedirs(os.path.join(d, "step-00000009.ckpt"))
    ck.save_step_sharded(_payload(2), 2, keep_last=1, block=True)
    dirs = _step_dirs(d)
    assert "step-00000002.ckpt" in dirs
    assert "step-00000001.ckpt" not in dirs  # rotated out, keep_last=1
    assert "step-00000009.ckpt" in dirs  # newer-incomplete left alone
    # an incomplete dir OLDER than the newest complete one is debris
    os.makedirs(os.path.join(d, "step-00000000.ckpt"))
    ck.save_step_sharded(_payload(3), 3, keep_last=1, block=True)
    assert "step-00000000.ckpt" not in _step_dirs(d)


def test_newest_restorable_prefers_highest_step(tmp_path):
    d = str(tmp_path)
    ck = Checkpointer(d)
    ck.save_latest_sharded(_payload(5))  # suspend save at step 5
    ck.save_step_sharded(_payload(8), 8, keep_last=2, block=True)
    assert ck.newest_restorable().endswith("step-00000008.ckpt")
    # a newer suspend save wins back
    ck.save_latest_sharded(_payload(11))
    assert ck.newest_restorable().endswith("latest.ckpt")


def test_interval_resume_bit_exact(tmp_path, devices8):
    """A crash after interval saves (no suspend artifact at all) must
    resume from the newest step checkpoint and replay to the exact end
    state of an uninterrupted run."""
    from test_lm_trainer import make_lm_trainer

    t_ref = make_lm_trainer(tmp_path / "ref", devices8)
    t_ref.fit()

    t_int = make_lm_trainer(
        tmp_path / "int", devices8, save_every_n_steps=3,
        keep_last_ckpts=2,
    )
    t_int.fit()
    # interval saves don't perturb training math
    assert_trees_equal(t_ref.state.params, t_int.state.params)
    d = str(tmp_path / "int")
    steps = _step_dirs(d)
    assert 1 <= len(steps) <= 2  # retention bound
    # simulate a crash that left ONLY interval checkpoints behind
    import shutil

    for n in ("best.ckpt", "latest.ckpt"):
        shutil.rmtree(os.path.join(d, n), ignore_errors=True)
    t_res = make_lm_trainer(
        tmp_path / "int", devices8, save_every_n_steps=3,
        keep_last_ckpts=2,
    )
    assert t_res.try_resume()
    assert (t_res.start_epoch, t_res.start_step) != (0, 0)
    t_res.fit()  # try_resume inside fit() is idempotent on the same dir
    assert_trees_equal(t_ref.state.params, t_res.state.params)
    assert int(jax.device_get(t_ref.state.step)) == int(
        jax.device_get(t_res.state.step)
    )


def test_legacy_latest_ranked_by_real_step(tmp_path):
    """ADVICE r5 #1: ``newest_restorable`` used to hardcode a legacy
    single-file ``latest.ckpt`` to step 0, so a strictly-OLDER interval
    checkpoint could win resume over a newer suspend save. The legacy
    step is now read from the msgpack payload."""
    from pytorch_distributed_tpu.utils.checkpoint import (
        legacy_checkpoint_step,
    )

    d = str(tmp_path)
    ck = Checkpointer(d)
    ck.save_step_sharded(_payload(100), 100, keep_last=4, block=True)
    ck.save_latest(_payload(1000))  # legacy single-file suspend save
    assert legacy_checkpoint_step(ck.latest_path) == 1000
    # the r5 bug: step-100 (sharded) would beat the step-1000 legacy file
    assert ck.newest_restorable() == ck.latest_path
    # and the ranking is by STEP, not by format: an older legacy file
    # correctly loses to a newer interval checkpoint
    ck.save_latest(_payload(50))
    assert ck.newest_restorable().endswith("step-00000100.ckpt")
