"""Sharded checkpointing (utils.checkpoint.save_sharded/load_sharded):
per-process block files + a manifest computed from sharding metadata, no
full-state gather on any rank. Single-process coverage here; the real
two-process no-gather guarantee is asserted in tests/test_multihost.py
(process_allgather patched to raise during save+resume)."""

import glob
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from pytorch_distributed_tpu.parallel import make_mesh
from pytorch_distributed_tpu.utils.checkpoint import (
    Checkpointer,
    load_sharded,
    save_sharded,
)


def payload_on_mesh(mesh):
    sh_model = NamedSharding(mesh, P(None, "model"))
    sh_repl = NamedSharding(mesh, P())
    rng = np.random.default_rng(0)
    return {
        "state": {
            "w_tp": jax.device_put(
                jnp.asarray(rng.normal(size=(8, 16)), jnp.float32), sh_model
            ),
            "b_repl": jax.device_put(
                jnp.asarray(rng.normal(size=(16,)), jnp.float32), sh_repl
            ),
            "step": jax.device_put(jnp.asarray(7, jnp.int32), sh_repl),
        },
        "epoch": 3,
        "step": 11,
        "best": 0.25,
    }


def test_roundtrip_bit_exact_with_shardings(devices8, tmp_path):
    mesh = make_mesh(devices8, data_parallel=4, model_parallel=2)
    payload = payload_on_mesh(mesh)
    d = os.fspath(tmp_path / "ck")
    save_sharded(d, payload)

    assert os.path.exists(os.path.join(d, "manifest.json"))
    # token-named data file: shard-<token>-00000.npz
    assert glob.glob(os.path.join(d, "shard-*-00000.npz"))

    shardings = jax.tree.map(lambda _: False, payload)
    shardings["state"] = {
        "w_tp": NamedSharding(mesh, P(None, "model")),
        "b_repl": NamedSharding(mesh, P()),
        "step": NamedSharding(mesh, P()),
    }
    back = load_sharded(d, payload, shardings)
    np.testing.assert_array_equal(
        np.asarray(back["state"]["w_tp"]), np.asarray(payload["state"]["w_tp"])
    )
    assert back["state"]["w_tp"].sharding.is_equivalent_to(
        payload["state"]["w_tp"].sharding, 2
    )
    np.testing.assert_array_equal(
        np.asarray(back["state"]["b_repl"]),
        np.asarray(payload["state"]["b_repl"]),
    )
    assert int(back["state"]["step"]) == 7
    assert int(back["epoch"]) == 3 and int(back["step"]) == 11
    assert float(back["best"]) == 0.25


def test_restore_onto_different_sharding(devices8, tmp_path):
    """Blocks reassemble across sharding changes: saved on (4, 2), restored
    with the axis split differently — the overlap assembly path."""
    mesh = make_mesh(devices8, data_parallel=4, model_parallel=2)
    payload = payload_on_mesh(mesh)
    d = os.fspath(tmp_path / "ck")
    save_sharded(d, payload)

    mesh2 = make_mesh(devices8, data_parallel=1, model_parallel=8)
    shardings = jax.tree.map(lambda _: False, payload)
    shardings["state"] = {
        "w_tp": NamedSharding(mesh2, P("model", None)),  # other dim!
        "b_repl": NamedSharding(mesh2, P()),
        "step": NamedSharding(mesh2, P()),
    }
    back = load_sharded(d, payload, shardings)
    np.testing.assert_array_equal(
        np.asarray(back["state"]["w_tp"]), np.asarray(payload["state"]["w_tp"])
    )


def test_manifest_records_block_layout(devices8, tmp_path):
    mesh = make_mesh(devices8, data_parallel=4, model_parallel=2)
    payload = payload_on_mesh(mesh)
    d = os.fspath(tmp_path / "ck")
    save_sharded(d, payload)
    with open(os.path.join(d, "manifest.json")) as f:
        manifest = json.load(f)
    w = manifest["leaves"]["state/w_tp"]
    assert w["shape"] == [8, 16]
    # model axis of 2 → two distinct column blocks
    starts = sorted(tuple(b["start"]) for b in w["blocks"])
    assert starts == [(0, 0), (0, 8)]
    # replicated leaf: one full block
    assert len(manifest["leaves"]["state/b_repl"]["blocks"]) == 1


def test_template_structure_mismatch_raises(devices8, tmp_path):
    mesh = make_mesh(devices8, data_parallel=4, model_parallel=2)
    payload = payload_on_mesh(mesh)
    d = os.fspath(tmp_path / "ck")
    save_sharded(d, payload)
    bad = dict(payload)
    bad["extra_key"] = 1.0
    with pytest.raises(KeyError, match="extra_key"):
        load_sharded(d, bad)


def test_checkpointer_sharded_replaces_legacy_file(devices8, tmp_path):
    """A legacy single-file latest.ckpt gives way to the sharded dir of the
    same name; has_latest/latest_is_sharded dispatch correctly."""
    mesh = make_mesh(devices8, data_parallel=4, model_parallel=2)
    ck = Checkpointer(os.fspath(tmp_path))
    ck.save_latest({"a": np.float32(1.0)})  # legacy file
    assert ck.has_latest() and not ck.latest_is_sharded()
    payload = payload_on_mesh(mesh)
    ck.save_latest_sharded(payload)
    assert ck.has_latest() and ck.latest_is_sharded()
    back = ck.load_latest_sharded(payload)
    np.testing.assert_array_equal(
        np.asarray(back["state"]["w_tp"]),
        np.asarray(payload["state"]["w_tp"]),
    )


def test_torn_save_detected(devices8, tmp_path):
    """A manifest-referenced data file carrying a different save's token
    (filesystem damage / manual copy) must refuse to load, not silently
    mix two training states."""
    mesh = make_mesh(devices8, data_parallel=4, model_parallel=2)
    payload = payload_on_mesh(mesh)
    d = os.fspath(tmp_path / "ck")
    save_sharded(d, payload)
    (f1,) = glob.glob(os.path.join(d, "shard-*-00000.npz"))
    with open(f1, "rb") as f:
        stale_bytes = f.read()  # belongs to save 1's token
    save_sharded(d, payload)  # a NEWER save (new token; GCs save 1's file)
    (f2,) = glob.glob(os.path.join(d, "shard-*-00000.npz"))
    with open(f2, "wb") as f:
        f.write(stale_bytes)  # wrong-token content behind the live name
    with pytest.raises(RuntimeError, match="torn checkpoint"):
        load_sharded(d, payload)


def test_crash_mid_save_keeps_previous_checkpoint(devices8, tmp_path):
    """THE durability property the token-named layout buys (ADVICE r3
    medium): a save that dies after writing data files but before the
    manifest commit leaves the PREVIOUS checkpoint fully restorable —
    token-named files mean the new save never clobbered it."""
    from pytorch_distributed_tpu.utils.checkpoint import _ShardedSave

    mesh = make_mesh(devices8, data_parallel=4, model_parallel=2)
    p1 = payload_on_mesh(mesh)
    d = os.fspath(tmp_path / "ck")
    save_sharded(d, p1)

    p2 = payload_on_mesh(mesh)
    p2["state"]["w_tp"] = jax.device_put(
        jnp.zeros((8, 16), jnp.float32),
        NamedSharding(mesh, P(None, "model")),
    )
    crash = _ShardedSave(d, p2)
    crash.write()  # data files land...
    # ...and the process dies before finalize(): no barrier, no manifest
    back = load_sharded(d, p1)
    np.testing.assert_array_equal(
        np.asarray(back["state"]["w_tp"]), np.asarray(p1["state"]["w_tp"])
    )


def test_successful_save_gcs_stale_shard_files(devices8, tmp_path):
    """A completed save removes superseded saves' data files (including a
    crashed save's orphans) — directories don't grow one shard file per
    save forever."""
    mesh = make_mesh(devices8, data_parallel=4, model_parallel=2)
    payload = payload_on_mesh(mesh)
    d = os.fspath(tmp_path / "ck")
    save_sharded(d, payload)
    save_sharded(d, payload)
    files = glob.glob(os.path.join(d, "shard-*.npz"))
    assert len(files) == 1  # single process: exactly one live shard file


def test_async_save_via_checkpointer(devices8, tmp_path):
    """block=False: snapshot returns immediately, the old best stays
    loadable until wait() commits, and after wait() the new best loads."""
    mesh = make_mesh(devices8, data_parallel=4, model_parallel=2)
    ck = Checkpointer(os.fspath(tmp_path))
    p1 = payload_on_mesh(mesh)
    ck.save_best_sharded(p1)  # blocking baseline save
    p2 = payload_on_mesh(mesh)
    p2["state"]["w_tp"] = jax.device_put(
        jnp.full((8, 16), 7.0, jnp.float32),
        NamedSharding(mesh, P(None, "model")),
    )
    ck.save_best_sharded(p2, block=False)
    # before the commit, the manifest still points at save 1
    back = load_sharded(ck.best_path, p1)
    np.testing.assert_array_equal(
        np.asarray(back["state"]["w_tp"]), np.asarray(p1["state"]["w_tp"])
    )
    ck.wait()
    back = ck.load_best(p2)
    np.testing.assert_array_equal(
        np.asarray(back["state"]["w_tp"]), np.asarray(p2["state"]["w_tp"])
    )


def test_load_best_incomplete_dir_raises_cleanly(devices8, tmp_path):
    """ADVICE r3 low: a best dir without a manifest (crashed save) gets
    the deliberate error, not a raw manifest.json FileNotFoundError."""
    ck = Checkpointer(os.fspath(tmp_path))
    os.makedirs(ck.best_path)
    assert not ck.has_best()
    assert not ck.best_is_sharded()
    with pytest.raises(FileNotFoundError, match="without a manifest"):
        ck.load_best({"a": np.float32(0.0)})


def test_duplicate_leaf_paths_rejected(devices8, tmp_path):
    """ADVICE r3 low: two leaves flattening to one path string must fail
    loudly at save time, not corrupt the second leaf at restore."""
    payload = {"a": {"b": np.float32(1.0)}, "a/b": np.float32(2.0)}
    with pytest.raises(ValueError, match="duplicate leaf paths"):
        save_sharded(os.fspath(tmp_path / "ck"), payload)


def test_incomplete_save_dir_is_not_latest(devices8, tmp_path):
    """A directory without a manifest (save died before completion) must
    not count as a restorable latest checkpoint."""
    ck = Checkpointer(os.fspath(tmp_path))
    os.makedirs(ck.latest_path)
    assert not ck.has_latest()
    assert not ck.latest_is_sharded()
