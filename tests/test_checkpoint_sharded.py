"""Sharded checkpointing (utils.checkpoint.save_sharded/load_sharded):
per-process block files + a manifest computed from sharding metadata, no
full-state gather on any rank. Single-process coverage here; the real
two-process no-gather guarantee is asserted in tests/test_multihost.py
(process_allgather patched to raise during save+resume)."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from pytorch_distributed_tpu.parallel import make_mesh
from pytorch_distributed_tpu.utils.checkpoint import (
    Checkpointer,
    load_sharded,
    save_sharded,
)


def payload_on_mesh(mesh):
    sh_model = NamedSharding(mesh, P(None, "model"))
    sh_repl = NamedSharding(mesh, P())
    rng = np.random.default_rng(0)
    return {
        "state": {
            "w_tp": jax.device_put(
                jnp.asarray(rng.normal(size=(8, 16)), jnp.float32), sh_model
            ),
            "b_repl": jax.device_put(
                jnp.asarray(rng.normal(size=(16,)), jnp.float32), sh_repl
            ),
            "step": jax.device_put(jnp.asarray(7, jnp.int32), sh_repl),
        },
        "epoch": 3,
        "step": 11,
        "best": 0.25,
    }


def test_roundtrip_bit_exact_with_shardings(devices8, tmp_path):
    mesh = make_mesh(devices8, data_parallel=4, model_parallel=2)
    payload = payload_on_mesh(mesh)
    d = os.fspath(tmp_path / "ck")
    save_sharded(d, payload)

    assert os.path.exists(os.path.join(d, "manifest.json"))
    assert os.path.exists(os.path.join(d, "shard-00000.npz"))

    shardings = jax.tree.map(lambda _: False, payload)
    shardings["state"] = {
        "w_tp": NamedSharding(mesh, P(None, "model")),
        "b_repl": NamedSharding(mesh, P()),
        "step": NamedSharding(mesh, P()),
    }
    back = load_sharded(d, payload, shardings)
    np.testing.assert_array_equal(
        np.asarray(back["state"]["w_tp"]), np.asarray(payload["state"]["w_tp"])
    )
    assert back["state"]["w_tp"].sharding.is_equivalent_to(
        payload["state"]["w_tp"].sharding, 2
    )
    np.testing.assert_array_equal(
        np.asarray(back["state"]["b_repl"]),
        np.asarray(payload["state"]["b_repl"]),
    )
    assert int(back["state"]["step"]) == 7
    assert int(back["epoch"]) == 3 and int(back["step"]) == 11
    assert float(back["best"]) == 0.25


def test_restore_onto_different_sharding(devices8, tmp_path):
    """Blocks reassemble across sharding changes: saved on (4, 2), restored
    with the axis split differently — the overlap assembly path."""
    mesh = make_mesh(devices8, data_parallel=4, model_parallel=2)
    payload = payload_on_mesh(mesh)
    d = os.fspath(tmp_path / "ck")
    save_sharded(d, payload)

    mesh2 = make_mesh(devices8, data_parallel=1, model_parallel=8)
    shardings = jax.tree.map(lambda _: False, payload)
    shardings["state"] = {
        "w_tp": NamedSharding(mesh2, P("model", None)),  # other dim!
        "b_repl": NamedSharding(mesh2, P()),
        "step": NamedSharding(mesh2, P()),
    }
    back = load_sharded(d, payload, shardings)
    np.testing.assert_array_equal(
        np.asarray(back["state"]["w_tp"]), np.asarray(payload["state"]["w_tp"])
    )


def test_manifest_records_block_layout(devices8, tmp_path):
    mesh = make_mesh(devices8, data_parallel=4, model_parallel=2)
    payload = payload_on_mesh(mesh)
    d = os.fspath(tmp_path / "ck")
    save_sharded(d, payload)
    with open(os.path.join(d, "manifest.json")) as f:
        manifest = json.load(f)
    w = manifest["leaves"]["state/w_tp"]
    assert w["shape"] == [8, 16]
    # model axis of 2 → two distinct column blocks
    starts = sorted(tuple(b["start"]) for b in w["blocks"])
    assert starts == [(0, 0), (0, 8)]
    # replicated leaf: one full block
    assert len(manifest["leaves"]["state/b_repl"]["blocks"]) == 1


def test_template_structure_mismatch_raises(devices8, tmp_path):
    mesh = make_mesh(devices8, data_parallel=4, model_parallel=2)
    payload = payload_on_mesh(mesh)
    d = os.fspath(tmp_path / "ck")
    save_sharded(d, payload)
    bad = dict(payload)
    bad["extra_key"] = 1.0
    with pytest.raises(KeyError, match="extra_key"):
        load_sharded(d, bad)


def test_checkpointer_sharded_replaces_legacy_file(devices8, tmp_path):
    """A legacy single-file latest.ckpt gives way to the sharded dir of the
    same name; has_latest/latest_is_sharded dispatch correctly."""
    mesh = make_mesh(devices8, data_parallel=4, model_parallel=2)
    ck = Checkpointer(os.fspath(tmp_path))
    ck.save_latest({"a": np.float32(1.0)})  # legacy file
    assert ck.has_latest() and not ck.latest_is_sharded()
    payload = payload_on_mesh(mesh)
    ck.save_latest_sharded(payload)
    assert ck.has_latest() and ck.latest_is_sharded()
    back = ck.load_latest_sharded(payload)
    np.testing.assert_array_equal(
        np.asarray(back["state"]["w_tp"]),
        np.asarray(payload["state"]["w_tp"]),
    )


def test_torn_save_detected(devices8, tmp_path):
    """A shard file left over from a different save (crash mid-save) must
    refuse to load, not silently mix two training states."""
    mesh = make_mesh(devices8, data_parallel=4, model_parallel=2)
    payload = payload_on_mesh(mesh)
    d = os.fspath(tmp_path / "ck")
    save_sharded(d, payload)
    import shutil

    stale = os.path.join(tmp_path, "stale.npz")
    shutil.copy(os.path.join(d, "shard-00000.npz"), stale)
    save_sharded(d, payload)  # a NEWER save (new token)
    shutil.copy(stale, os.path.join(d, "shard-00000.npz"))  # torn mix
    with pytest.raises(RuntimeError, match="torn checkpoint"):
        load_sharded(d, payload)


def test_incomplete_save_dir_is_not_latest(devices8, tmp_path):
    """A directory without a manifest (save died before completion) must
    not count as a restorable latest checkpoint."""
    ck = Checkpointer(os.fspath(tmp_path))
    os.makedirs(ck.latest_path)
    assert not ck.has_latest()
    assert not ck.latest_is_sharded()
