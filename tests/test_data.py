"""Packed-record format, native reader parity, transforms, loader."""

import io
import os

import numpy as np
import pytest

from pytorch_distributed_tpu.data import (
    DataLoader,
    DistributedSampler,
    PackedRecordReader,
    PackedRecordWriter,
    SyntheticImageClassification,
)
from pytorch_distributed_tpu.data import native
from pytorch_distributed_tpu.data import transforms as T


@pytest.fixture
def tprc_file(tmp_path):
    rng = np.random.default_rng(0)
    records = [rng.bytes(int(n)) for n in rng.integers(1, 5000, size=50)]
    records.append(b"")  # zero-length record edge case
    path = str(tmp_path / "test.tprc")
    with PackedRecordWriter(path) as w:
        w.write_all(records)
    return path, records


def test_packed_record_roundtrip_python(tprc_file):
    path, records = tprc_file
    with PackedRecordReader(path, use_native=False) as r:
        assert len(r) == len(records)
        for i, rec in enumerate(records):
            assert r.read(i) == rec
        got = r.read_batch([3, 1, 4, 1, 5])
        assert got == [records[3], records[1], records[4], records[1], records[5]]


def test_native_reader_matches_python(tprc_file):
    if not native.available():
        pytest.skip("no C++ toolchain")
    path, records = tprc_file
    with PackedRecordReader(path, use_native=True) as r:
        assert len(r) == len(records)
        for i, rec in enumerate(records):
            assert r.read(i) == rec
        assert r.read_batch([0, 7, 2]) == [records[0], records[7], records[2]]


def test_native_detects_corruption(tmp_path):
    if not native.available():
        pytest.skip("no C++ toolchain")
    path = str(tmp_path / "c.tprc")
    with PackedRecordWriter(path) as w:
        w.write(b"hello world, a record long enough to corrupt")
    # flip a payload byte (last byte of the file)
    with open(path, "r+b") as f:
        f.seek(-1, os.SEEK_END)
        b = f.read(1)
        f.seek(-1, os.SEEK_END)
        f.write(bytes([b[0] ^ 0xFF]))
    with PackedRecordReader(path, use_native=True) as r:
        with pytest.raises(IOError):
            r.read(0)
    with PackedRecordReader(path, use_native=False) as r:
        with pytest.raises(IOError):
            r.read(0)
        assert r.read(0, verify_crc=False)  # corruption invisible without crc


def test_transforms_shapes_and_ranges():
    from PIL import Image

    rng = np.random.default_rng(1)
    img = Image.fromarray(
        rng.integers(0, 255, size=(300, 500, 3), dtype=np.uint8), "RGB"
    )
    train = T.train_transform(size=64)
    out = train(img, np.random.default_rng(2))
    assert out.shape == (64, 64, 3)
    assert out.dtype == np.float32

    ev = T.eval_transform(size=64, resize=72)
    out2 = ev(img)
    assert out2.shape == (64, 64, 3)
    # eval transform is deterministic
    np.testing.assert_array_equal(out2, ev(img))


def test_center_crop_and_resize_geometry():
    from PIL import Image

    img = Image.new("RGB", (400, 200))
    resized = T.Resize(100)(img)
    assert (resized.width, resized.height) == (200, 100)  # short side → 100
    cropped = T.CenterCrop(64)(resized)
    assert (cropped.width, cropped.height) == (64, 64)


def test_synthetic_dataset_deterministic():
    ds = SyntheticImageClassification(size=16, image_size=8, num_classes=4)
    img1, label1 = ds[3]
    img2, label2 = ds[3]
    np.testing.assert_array_equal(img1, img2)
    assert label1 == label2 == 3
    assert img1.shape == (8, 8, 3)


@pytest.mark.parametrize("num_workers,prefetch", [(0, 1), (2, 3)])
def test_loader_batches_and_seek(num_workers, prefetch):
    ds = SyntheticImageClassification(size=40, image_size=4, num_classes=10)
    sampler = DistributedSampler(len(ds), 2, 0, seed=1)
    sampler.set_epoch(0)
    loader = DataLoader(
        ds, batch_size=4, sampler=sampler, num_workers=num_workers, prefetch=prefetch
    )
    batches = list(loader)
    assert len(batches) == len(loader) == 5  # 20 local samples / bs 4
    assert batches[0]["image"].shape == (4, 4, 4, 3)
    assert batches[0]["label"].dtype == np.int32

    # seek to batch 2: identical to slicing the full epoch (resume parity)
    seeked = list(loader.iter_batches(start_batch=2))
    assert len(seeked) == 3
    for a, b in zip(seeked, batches[2:]):
        np.testing.assert_array_equal(a["image"], b["image"])
        np.testing.assert_array_equal(a["label"], b["label"])


def test_imagenet_packed_split(tmp_path):
    from PIL import Image

    from pytorch_distributed_tpu.data.imagenet import ImageNet, write_imagenet_split

    rng = np.random.default_rng(3)

    def samples():
        for k in range(6):
            img = Image.fromarray(
                rng.integers(0, 255, size=(32, 48, 3), dtype=np.uint8), "RGB"
            )
            buf = io.BytesIO()
            img.save(buf, "JPEG")
            yield buf.getvalue(), k % 3

    n = write_imagenet_split(str(tmp_path / "val.tprc"), samples())
    assert n == 6
    ds = ImageNet(
        split="val",
        data_dir=str(tmp_path),
        transform=T.eval_transform(size=16, resize=20),
    )
    assert len(ds) == 6
    img, label = ds[4]
    assert img.shape == (16, 16, 3)
    assert label == 1
    loader = ds.loader(batch_size=3, num_workers=0)
    batch = next(iter(loader))
    assert batch["image"].shape == (3, 16, 16, 3)


def test_writer_exception_publishes_nothing(tmp_path):
    # A crash mid-pack must not leave a valid-looking partial file.
    path = str(tmp_path / "crash.tprc")
    with pytest.raises(RuntimeError):
        with PackedRecordWriter(path) as w:
            w.write(b"one")
            raise RuntimeError("source iterator died")
    assert not os.path.exists(path)
    assert list(os.listdir(tmp_path)) == []  # no stray temp files


def test_corrupt_record_count_native(tmp_path):
    if not native.available():
        pytest.skip("no C++ toolchain")
    path = str(tmp_path / "bign.tprc")
    with PackedRecordWriter(path) as w:
        w.write(b"abc")
    # corrupt n to a huge value: native open must fail cleanly, not abort
    with open(path, "r+b") as f:
        f.seek(8)
        f.write((2**60).to_bytes(8, "little"))
    with pytest.raises(IOError):
        PackedRecordReader(path, use_native=True)


def test_augmentation_rng_is_resume_deterministic(tmp_path):
    """Resumed iteration must reproduce the same random crops/flips."""
    import io as _io

    from PIL import Image

    from pytorch_distributed_tpu.data.imagenet import ImageNet, write_imagenet_split

    rng = np.random.default_rng(5)

    def samples():
        for k in range(8):
            img = Image.fromarray(
                rng.integers(0, 255, size=(40, 40, 3), dtype=np.uint8), "RGB"
            )
            buf = _io.BytesIO()
            img.save(buf, "JPEG")
            yield buf.getvalue(), k

    write_imagenet_split(str(tmp_path / "train.tprc"), samples())
    ds = ImageNet(
        split="train",
        data_dir=str(tmp_path),
        transform=T.train_transform(size=16),  # random crop + flip
    )
    sampler = DistributedSampler(len(ds), 1, 0, seed=2)
    sampler.set_epoch(1)
    loader = DataLoader(ds, batch_size=2, sampler=sampler, num_workers=0, seed=9)
    full = list(loader)
    resumed = list(loader.iter_batches(start_batch=2))
    for a, b in zip(resumed, full[2:]):
        np.testing.assert_array_equal(a["image"], b["image"])  # same augmentations
