"""Telemetry runtime (ISSUE 4): device metrics ring, spans, goodput,
latency percentiles, logger hardening, and the sync-free trainer path.

The two load-bearing proofs:
- the ring path adds NOTHING to the compiled step: a ``no_recompile``-
  guarded LM step (jit-cache growth + implicit-transfer guard) stays
  green with telemetry enabled;
- the logged metric series is bit-identical to the seed blocking
  ``float()`` path (same f32 scalars, one hop through the buffer).
"""

import gzip
import json
import os
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_tpu.telemetry import (
    NULL_TRACER,
    DeviceMetricsRing,
    GoodputLedger,
    LatencySeries,
    SpanTracer,
    percentiles,
)
from pytorch_distributed_tpu.telemetry.goodput import GOODPUT_CATEGORIES

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---- device metrics ring -------------------------------------------------


def test_ring_wraparound_drain_order_and_bit_exact_roundtrip():
    """2.5 windows through the ring: every record comes back, in push
    order, with the exact f32 bit pattern that went in."""
    vals = np.float32([0.1, 1 / 3, np.pi, 7e-8, 1234.5678, -0.0,
                       2.5e38, 1e-38, 42.0, 5.5])
    ring = DeviceMetricsRing(["loss", "tokens"], capacity=4)
    recs = []
    for i, v in enumerate(vals):
        recs += ring.append(
            {"loss": jnp.float32(v), "tokens": jnp.float32(i)}, step=i
        )
    # lagged drain: with 10 pushes at capacity 4, two windows filled but
    # only the first has been harvested so far
    assert len(recs) == 4
    recs += ring.flush()
    assert [r["step"] for r in recs] == list(range(10))
    for i, r in enumerate(recs):
        # bit-identical: f32 → f32 through the buffer, no re-rounding
        assert np.float32(r["loss"]) == vals[i]
        assert r["tokens"] == float(i)
    assert ring.buffered == 0
    assert ring.pushed == ring.drained == 10


def test_ring_lagged_window_semantics():
    """Filling window N returns window N-1 (whose async host copy is
    long done); nothing is returned before the first window fills."""
    ring = DeviceMetricsRing(["x"], capacity=3)
    outs = [ring.append({"x": jnp.float32(i)}, i=i) for i in range(7)]
    assert [len(o) for o in outs] == [0, 0, 0, 0, 0, 3, 0]
    assert [r["i"] for r in outs[5]] == [0, 1, 2]
    tail = ring.flush()
    assert [r["i"] for r in tail] == [3, 4, 5, 6]


def test_ring_validation():
    with pytest.raises(ValueError):
        DeviceMetricsRing(["a"], capacity=0)
    with pytest.raises(ValueError):
        DeviceMetricsRing([])
    with pytest.raises(ValueError):
        DeviceMetricsRing(["a", "a"])


def test_ring_replicated_sharding(devices8):
    """Metrics from a shard_map step are mesh-replicated global arrays;
    the ring buffer must live on the same devices or jit rejects the
    mix."""
    from pytorch_distributed_tpu.parallel import make_mesh
    from pytorch_distributed_tpu.parallel import mesh as mesh_lib

    mesh = make_mesh(devices8, data_parallel=2, seq_parallel=2,
                     model_parallel=2)
    sh = mesh_lib.replicated_sharding(mesh)
    ring = DeviceMetricsRing(["x"], capacity=2, sharding=sh)
    v = jax.device_put(jnp.float32(3.25), sh)
    recs = ring.append({"x": v}, step=0)
    recs += ring.append({"x": v}, step=1)
    recs += ring.flush()
    assert [r["x"] for r in recs] == [3.25, 3.25]


def test_no_recompile_guarded_lm_step_with_telemetry():
    """The acceptance gate: with the ring enabled, the compiled LM step
    adds ZERO host syncs and ZERO recompiles — the jit cache stops
    growing after warmup and the transfer guard never trips."""
    from pytorch_distributed_tpu.analysis import no_recompile
    from pytorch_distributed_tpu.models.transformer import tiny_config
    from pytorch_distributed_tpu.ops.optim import build_optimizer
    from pytorch_distributed_tpu.ops.schedules import warmup_cosine
    from pytorch_distributed_tpu.parallel import make_mesh
    from pytorch_distributed_tpu.parallel import mesh as mesh_lib
    from pytorch_distributed_tpu.train.lm import (
        create_lm_state,
        make_lm_train_step,
        shift_labels,
    )
    from pytorch_distributed_tpu.train.lm_trainer import shard_lm_batch

    mesh = make_mesh(jax.devices()[:1], data_parallel=1, seq_parallel=1,
                     model_parallel=1)
    cfg = tiny_config(attention="dense")
    tx = build_optimizer("adamw", warmup_cosine(1e-3, 10), weight_decay=0.0)
    state = create_lm_state(cfg, tx, jax.random.key(0))
    state = jax.device_put(state, mesh_lib.replicated_sharding(mesh))
    step = no_recompile(
        make_lm_train_step(mesh, config=cfg), warmup_steps=2
    )
    ring = DeviceMetricsRing(
        ["loss", "tokens"], capacity=2,
        sharding=mesh_lib.replicated_sharding(mesh),
    )
    rng = np.random.default_rng(0)
    recs = []
    for i in range(6):
        tokens = rng.integers(1, cfg.vocab_size, (2, 32)).astype(np.int32)
        labels, weights = shift_labels(tokens)
        batch = shard_lm_batch(mesh, {
            "tokens": tokens, "labels": labels, "weights": weights,
        })
        state, metrics = step(state, batch)  # raises GuardViolation on hazard
        recs += ring.append(metrics, step=i)
    recs += ring.flush()
    assert step.stats.recompiles_after_warmup == 0
    assert len(recs) == 6 and all(np.isfinite(r["loss"]) for r in recs)


# ---- spans ---------------------------------------------------------------


def test_span_nesting_and_chrome_trace_validity(tmp_path):
    t = SpanTracer()
    with t.span("outer", step=1):
        time.sleep(0.002)
        with t.span("inner"):
            time.sleep(0.002)
        with t.span("inner"):
            pass
    path = t.save(os.fspath(tmp_path / "spans.trace.json"))
    data = json.load(open(path))  # valid JSON on disk
    events = data["traceEvents"]
    assert any(e["ph"] == "M" and e["name"] == "process_name"
               for e in events)
    spans = [e for e in events if e["ph"] == "X"]
    assert sorted(e["name"] for e in spans) == ["inner", "inner", "outer"]
    for e in spans:
        assert e["dur"] >= 0 and {"ts", "pid", "tid"} <= set(e)
    outer = next(e for e in spans if e["name"] == "outer")
    for inner in (e for e in spans if e["name"] == "inner"):
        # containment is what lets Perfetto rebuild the stack
        assert outer["ts"] <= inner["ts"]
        assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1
    assert outer["args"] == {"step": 1}


def test_span_disabled_records_nothing():
    assert NULL_TRACER.enabled is False
    with NULL_TRACER.span("x"):
        pass
    assert NULL_TRACER.events() == []
    t = SpanTracer(enabled=False)
    with t.span("y"):
        pass
    assert t.events() == []


# ---- goodput -------------------------------------------------------------


def test_goodput_classified_times_sum_to_wall():
    g = GoodputLedger()
    g.start()
    with g.timed("data_wait"):
        time.sleep(0.01)
    with g.timed("checkpoint"):
        time.sleep(0.005)
    t0 = time.perf_counter()
    time.sleep(0.002)
    g.add("stall", time.perf_counter() - t0)  # measured, like the watchdog
    r = g.report()
    classified = sum(r[f"{c}_s"] for c in GOODPUT_CATEGORIES)
    # seconds: productive is the remainder, so the classes sum to wall
    assert r["productive_s"] + classified == pytest.approx(r["wall_s"])
    # fractions sum to 1 by construction
    fracs = r["goodput_frac"] + sum(
        r[f"{c}_frac"] for c in GOODPUT_CATEGORIES
    )
    assert fracs == pytest.approx(1.0)
    assert r["data_wait_s"] >= 0.01 and r["checkpoint_s"] >= 0.005
    assert r["stall_s"] >= 0.002


def test_goodput_overcounted_classes_still_sum_to_one():
    g = GoodputLedger()
    g.start()
    g.add("compile", 1e6)  # pathological over-attribution
    r = g.report()
    assert r["goodput_frac"] == 0.0
    fracs = r["goodput_frac"] + sum(
        r[f"{c}_frac"] for c in GOODPUT_CATEGORIES
    )
    assert fracs == pytest.approx(1.0)


def test_goodput_rejects_unknown_category_and_negative():
    g = GoodputLedger()
    with pytest.raises(ValueError):
        g.add("naps", 1.0)
    with pytest.raises(ValueError):
        g.add("stall", -1.0)


def test_watchdog_feeds_stall_time_to_ledger():
    from pytorch_distributed_tpu.resilience.watchdog import Watchdog

    g = GoodputLedger()
    with Watchdog(0.15, poll_s=0.02, ledger=g) as w:
        w.beat()
        deadline = time.monotonic() + 5.0
        while w.stalls == 0 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert w.stalls == 1
        w.beat()  # clearing the stall attributes the whole gap
    assert g.seconds("stall") >= 0.15


# ---- latency -------------------------------------------------------------


def test_latency_percentiles_match_numpy_reference():
    rng = np.random.default_rng(0)
    vals = rng.exponential(0.05, size=257)
    s = LatencySeries("ttft")
    for v in vals:
        s.observe(v)
    out = s.summary("ttft")
    assert out["ttft_count"] == 257
    assert out["ttft_mean_s"] == pytest.approx(float(vals.mean()))
    assert out["ttft_max_s"] == pytest.approx(float(vals.max()))
    for q in (50, 95, 99):
        assert out[f"ttft_p{q}_s"] == pytest.approx(
            float(np.percentile(vals, q))
        )
    ps = percentiles(vals, qs=(50, 95))
    assert ps["p50"] == pytest.approx(float(np.percentile(vals, 50)))
    assert percentiles([]) == {}
    assert LatencySeries().summary("x") == {"x_count": 0}


def test_latency_edge_cases_empty_single_and_all_equal():
    """ISSUE 8 satellite: the degenerate series a short or idle run
    produces — empty, one sample, all-equal — summarize without NaNs,
    and every percentile of a constant/singleton series IS the value."""
    # empty: counts only, no stat keys to trip a renderer
    empty = LatencySeries("e").summary("e")
    assert empty == {"e_count": 0}
    assert percentiles([]) == {}
    assert percentiles([], qs=(1, 50, 99.9)) == {}
    # single sample: every percentile is the sample, spread is zero
    s = LatencySeries("one")
    s.observe(0.25)
    out = s.summary("one")
    assert out["one_count"] == 1
    assert out["one_mean_s"] == out["one_max_s"] == 0.25
    for q in (50, 95, 99):
        assert out[f"one_p{q}_s"] == 0.25
    assert percentiles([0.25], qs=(0, 50, 100)) == {
        "p0": 0.25, "p50": 0.25, "p100": 0.25
    }
    # all-equal: percentiles collapse to the value (no interpolation
    # artifacts), mean/max agree, nothing is NaN
    eq = LatencySeries("c")
    for _ in range(17):
        eq.observe(1.5)
    out = eq.summary("c")
    assert out["c_count"] == 17
    for k, v in out.items():
        if k != "c_count":
            assert v == 1.5, k
    # and a fractional q on an all-equal series is still exact
    assert percentiles([2.0] * 5, qs=(99.9,)) == {"p99.9": 2.0}


# ---- MetricsLogger hardening --------------------------------------------


def test_metrics_logger_reopen_appends_not_truncates(tmp_path):
    path = os.fspath(tmp_path / "m.jsonl")
    with __import__(
        "pytorch_distributed_tpu.utils.profiling", fromlist=["MetricsLogger"]
    ).MetricsLogger(path) as log:
        log.log(kind="train", step=1)
    # a reopened path APPENDS (a resumed run extends its history)
    from pytorch_distributed_tpu.utils.profiling import MetricsLogger

    with MetricsLogger(path) as log:
        log.log(kind="train", step=2)
    recs = [json.loads(l) for l in open(path)]
    assert [r["step"] for r in recs] == [1, 2]


def test_metrics_logger_durable_before_close(tmp_path):
    """Line-buffered: a crash after log() cannot lose the record."""
    from pytorch_distributed_tpu.utils.profiling import MetricsLogger

    path = os.fspath(tmp_path / "m.jsonl")
    log = MetricsLogger(path)
    log.log(kind="train", step=7)
    recs = [json.loads(l) for l in open(path)]  # read BEFORE close
    assert recs and recs[0]["step"] == 7
    log.close()
    log.close()  # idempotent


def test_metrics_logger_rank0_gating_internal(tmp_path, monkeypatch):
    from pytorch_distributed_tpu.utils import profiling

    path = os.fspath(tmp_path / "m.jsonl")
    monkeypatch.setattr(
        profiling.MetricsLogger, "_is_rank0", staticmethod(lambda: False)
    )
    log = profiling.MetricsLogger(path)
    log.log(kind="train", step=1)
    log.close()
    assert not os.path.exists(path)  # non-rank-0: gated inside the class
    log = profiling.MetricsLogger(path, rank0_only=False)
    log.log(kind="train", step=1)
    log.close()
    assert os.path.exists(path)  # per-process stream opts out


def test_metrics_logger_size_capped_rotation(tmp_path):
    """ISSUE 8 satellite: with ``max_bytes`` set, a long run's stream
    rotates to <path>.1 and keeps writing — total disk bounded by ~2x
    the cap, every record in exactly one generation, no torn lines."""
    from pytorch_distributed_tpu.utils.profiling import MetricsLogger

    path = os.fspath(tmp_path / "m.jsonl")
    with MetricsLogger(path, max_bytes=2048) as log:
        for i in range(200):
            log.log(kind="train", step=i, pad="x" * 64)
        rotations = log.rotations
    assert rotations >= 1
    assert os.path.exists(f"{path}.1")
    assert os.path.getsize(path) <= 2048 + 256  # cap + one record slack
    # both generations parse cleanly line by line (record-aligned
    # rotation: no torn records at the boundary)
    newest = [json.loads(l) for l in open(path)]
    rotated = [json.loads(l) for l in open(f"{path}.1")]
    steps = [r["step"] for r in rotated] + [r["step"] for r in newest]
    # the newest history is contiguous and ends at the last record
    assert steps == list(range(steps[0], 200))
    assert steps[-1] == 199


def test_metrics_logger_reopen_after_rotation_appends(tmp_path):
    """Rotation regression: a resumed run reopening a rotated stream
    appends to the ACTIVE generation and keeps rotating from there."""
    from pytorch_distributed_tpu.utils.profiling import MetricsLogger

    path = os.fspath(tmp_path / "m.jsonl")
    with MetricsLogger(path, max_bytes=512) as log:
        for i in range(20):
            log.log(step=i, pad="y" * 48)
    with MetricsLogger(path, max_bytes=512) as log:
        log.log(step=99)
    newest = [json.loads(l) for l in open(path)]
    assert newest[-1]["step"] == 99
    # the pre-reopen tail the resumed run appended AFTER is still there
    assert len(newest) >= 2 or os.path.exists(f"{path}.1")


# ---- trace_device_busy_s multi-run aggregation ---------------------------


def _write_trace_run(trace_dir, run, offset_us, durs_us):
    d = os.path.join(trace_dir, "plugins", "profile", run)
    os.makedirs(d, exist_ok=True)
    events = [{
        "ph": "M", "name": "process_name", "pid": 1,
        "args": {"name": "/device:TPU:0"},
    }]
    ts = offset_us
    for dur in durs_us:
        events.append({"ph": "X", "pid": 1, "tid": 1, "name": "op",
                       "ts": ts, "dur": dur})
        ts += dur + 10  # 10 us gaps
    with gzip.open(os.path.join(d, "host.trace.json.gz"), "wt") as f:
        json.dump({"traceEvents": events}, f)


def test_trace_device_busy_aggregates_across_runs(tmp_path):
    """The old code silently read only the newest ``plugins/profile/*``
    run; two runs must now aggregate (busy and span summed)."""
    from pytorch_distributed_tpu.utils.profiling import trace_device_busy_s

    d = os.fspath(tmp_path)
    _write_trace_run(d, "run_a", 0, [100, 200])  # busy 300, span 310
    one = trace_device_busy_s(d)
    assert one == pytest.approx((300e-6, 310e-6))
    _write_trace_run(d, "run_b", 50_000, [400])  # busy 400, span 400
    busy, span = trace_device_busy_s(d)
    assert busy == pytest.approx(700e-6)
    assert span == pytest.approx(710e-6)
    assert trace_device_busy_s(os.fspath(tmp_path / "empty")) is None


# ---- trainer integration: bit-identical series ---------------------------


def _lm_metrics(flush_every, save_dir):
    from pytorch_distributed_tpu.data.tokens import SyntheticTokens
    from pytorch_distributed_tpu.models.transformer import tiny_config
    from pytorch_distributed_tpu.parallel import make_mesh
    from pytorch_distributed_tpu.train import LMTrainer, LMTrainerConfig

    mesh = make_mesh(jax.devices()[:1], data_parallel=1, seq_parallel=1,
                     model_parallel=1)
    cfg = LMTrainerConfig(
        epochs=1, batch_size=2, lr=1e-2, save_dir=os.fspath(save_dir),
        num_workers=0, log_every=1, warmup_steps=0,
        flush_every=flush_every,
    )
    train = SyntheticTokens(size=12, seq_len=32, vocab_size=128)
    val = SyntheticTokens(size=8, seq_len=32, vocab_size=128, seed=9)
    t = LMTrainer(tiny_config(attention="dense"), train, val, cfg,
                  mesh=mesh)
    t.fit()
    t.metrics_log.close()
    return [json.loads(l)
            for l in open(os.path.join(save_dir, "metrics.jsonl"))]


def test_lm_trainer_ring_series_bit_identical_to_blocking(tmp_path):
    """The satellite acceptance: routing the log path through the drained
    device ring leaves the logged loss series BIT-identical to the seed
    blocking float() path, and emits a goodput record."""
    legacy = _lm_metrics(0, tmp_path / "legacy")
    ring = _lm_metrics(3, tmp_path / "ring")
    lt = [r for r in legacy if r["kind"] == "train"]
    rt = [r for r in ring if r["kind"] == "train"]
    assert len(lt) == len(rt) > 0
    for a, b in zip(lt, rt):
        assert (a["epoch"], a["step"]) == (b["epoch"], b["step"])
        assert a["loss"] == b["loss"]  # bit-identical, not approx
        assert a["tokens"] == b["tokens"]
    gp = [r for r in ring if r["kind"] == "goodput"]
    assert len(gp) == 1
    fracs = gp[0]["goodput_frac"] + sum(
        gp[0][f"{c}_frac"] for c in GOODPUT_CATEGORIES
    )
    assert fracs == pytest.approx(1.0)
    assert gp[0]["compile_s"] > 0  # first dispatch attributed


# ---- serving latency -----------------------------------------------------


def _tiny_scheduler(tmp_path=None, **kw):
    from pytorch_distributed_tpu.models.transformer import (
        TransformerLM,
        tiny_config,
    )
    from pytorch_distributed_tpu.serving import Scheduler

    cfg = tiny_config(attention="dense", max_seq_len=64)
    params = TransformerLM(cfg).init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return cfg, Scheduler(cfg, params, n_slots=2, block_len=8,
                          prefill_chunk=8, **kw)


def test_scheduler_latency_percentiles_and_request_records(tmp_path):
    from pytorch_distributed_tpu.utils.profiling import MetricsLogger

    path = os.fspath(tmp_path / "serve.jsonl")
    tracer = SpanTracer()
    with MetricsLogger(path) as mlog:
        cfg, s = _tiny_scheduler(tracer=tracer, metrics_log=mlog)
        rng = np.random.default_rng(0)
        for l in (5, 9, 14):
            s.submit(rng.integers(1, cfg.vocab_size, l).astype(np.int32),
                     4)
        streams = s.drain()
        m = s.metrics()
        mlog.log(kind="serving_summary", **m)
    assert len(streams) == 3
    # one TTFT per request; inter-token gaps exclude the first token
    assert m["ttft_count"] == 3
    assert m["token_lat_count"] == m["tokens_out"] - 3
    assert m["queue_wait_count"] == 3
    assert 0 <= m["ttft_p50_s"] <= m["ttft_p95_s"] <= m["ttft_max_s"]
    assert m["queue_wait_p50_s"] >= 0
    # spans from the scheduler's tick
    names = {e["name"] for e in tracer.events()}
    assert {"admission", "prefill_chunk", "decode_tick"} <= names
    # per-request JSONL records carry the raw material for the report
    recs = [json.loads(l) for l in open(path)]
    reqs = [r for r in recs if r["kind"] == "request"]
    assert len(reqs) == 3
    for r in reqs:
        assert r["ttft_s"] >= 0 and r["queue_wait_s"] >= 0
        assert len(r["token_gaps_s"]) == r["new_tokens"] - 1
    # numpy-reference check of the reported percentiles
    ttfts = np.asarray([r["ttft_s"] for r in reqs])
    assert m["ttft_p50_s"] == pytest.approx(
        float(np.percentile(s.ttft.values, 50))
    )
    assert np.percentile(ttfts, 50) == pytest.approx(
        m["ttft_p50_s"], abs=2e-6  # records round to 1 us
    )


# ---- telemetry_report ----------------------------------------------------


def test_telemetry_report_renders_goodput_and_latency(tmp_path):
    """From JSONL alone: a goodput breakdown summing to 1 and TTFT +
    per-token p50/p95 — the acceptance-criteria artifact."""
    train_path = os.fspath(tmp_path / "train.jsonl")
    with open(train_path, "w") as f:
        for step in range(4):
            f.write(json.dumps(
                {"kind": "train", "epoch": 0, "step": step,
                 "loss": 5.0 - step * 0.1, "tokens": 124.0}
            ) + "\n")
        f.write(json.dumps(
            {"kind": "epoch_timing", "epoch": 0, "steps": 4,
             "mean_ms": 12.5, "tokens_per_s": 9920.0}
        ) + "\n")
        f.write(json.dumps({
            "kind": "goodput", "wall_s": 10.0, "productive_s": 6.0,
            "goodput_frac": 0.6, "productive_frac": 0.6,
            "compile_s": 2.0, "compile_frac": 0.2,
            "data_wait_s": 1.0, "data_wait_frac": 0.1,
            "checkpoint_s": 1.0, "checkpoint_frac": 0.1,
            "rollback_s": 0.0, "rollback_frac": 0.0,
            "stall_s": 0.0, "stall_frac": 0.0,
        }) + "\n")
    serve_path = os.fspath(tmp_path / "serve.jsonl")
    rng = np.random.default_rng(1)
    ttfts, gaps = [], []
    with open(serve_path, "w") as f:
        for rid in range(8):
            t = float(rng.uniform(0.05, 0.5))
            g = [float(x) for x in rng.uniform(0.001, 0.02, 5)]
            ttfts.append(t)
            gaps += g
            f.write(json.dumps(
                {"kind": "request", "rid": rid, "prompt_len": 16,
                 "new_tokens": 6, "queue_wait_s": 0.01, "ttft_s": t,
                 "token_gaps_s": g}
            ) + "\n")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts/telemetry_report.py"),
         train_path, serve_path, "--json", "--require", "goodput,serving"],
        capture_output=True, text=True, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["goodput_frac"] == pytest.approx(0.6)
    frac_sum = out["goodput_frac"] + sum(
        out[f"goodput_{c}_frac"] for c in GOODPUT_CATEGORIES
    )
    assert frac_sum == pytest.approx(1.0)
    # the report rounds ms to 3 decimals
    assert out["serving_ttft_p50_ms"] == pytest.approx(
        float(np.percentile(ttfts, 50)) * 1e3, abs=1e-3
    )
    assert out["serving_token_lat_p95_ms"] == pytest.approx(
        float(np.percentile(gaps, 95)) * 1e3, abs=1e-3
    )
    assert out["train_last_loss"] == pytest.approx(4.7)
    # --require fails when a section is missing
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts/telemetry_report.py"),
         serve_path, "--require", "goodput"],
        capture_output=True, text=True, cwd=REPO,
    )
    assert proc.returncode != 0
