"""Child process for the 2-process localhost rendezvous tests.

Launched by tests/test_multihost.py with the reference's env contract
(MASTER_IP/MASTER_PORT/WORLD_SIZE/RANK, ``restnet_ddp.py:87-94``) on the CPU
backend with 4 virtual local devices per process → 8 global. Runs the real
DDP code path: ``init_process_group`` → global ``make_mesh`` → ``Trainer``
on synthetic data.

Modes (argv[1]):
  train    fit() a tiny run to completion, print a JSON result line with a
           parameter digest so the parent can assert cross-host agreement.
  suspend  train with many epochs and suspend_sync_every=1; the parent
           SIGTERMs ONE rank mid-epoch and both processes must checkpoint
           (rank 0) and yield together. Touches <save_dir>/started.<rank>
           once training has begun so the parent knows when to fire.
  lm       LMTrainer over a dp2×sp2×tp2 GLOBAL mesh: ring attention and
           tensor parallelism span the two processes, so the checkpoint
           payload's gather_global really runs its cross-process
           process_allgather collective (TP-sharded leaves are not locally
           addressable). Prints the same JSON result line as ``train``.
"""

import json
import os
import sys

# Backend setup must precede the jax import (see tests/conftest.py): the
# axon plugin would otherwise claim the TPU tunnel from both processes.
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def run_lm(save_dir: str) -> None:
    from pytorch_distributed_tpu.data import SyntheticTokens
    from pytorch_distributed_tpu.models.transformer import tiny_config
    from pytorch_distributed_tpu.parallel import make_mesh
    from pytorch_distributed_tpu.parallel.distributed import get_rank, get_world_size
    from pytorch_distributed_tpu.train import LMTrainer, LMTrainerConfig

    mesh = make_mesh(data_parallel=2, seq_parallel=2, model_parallel=2)
    model_cfg = tiny_config(
        attention="ring", model_axis="model", tp_size=2, dropout=0.1
    )
    cfg = LMTrainerConfig(epochs=1, batch_size=2, lr=1e-2, save_dir=save_dir,
                          num_workers=0, log_every=2)
    train = SyntheticTokens(size=16, seq_len=32, vocab_size=128)
    val = SyntheticTokens(size=8, seq_len=32, vocab_size=128, seed=9)
    trainer = LMTrainer(model_cfg, train, val, cfg, mesh=mesh)
    summary = trainer.fit()
    # sanity: the TP qkv kernels really span processes (gather_global had
    # to run its cross-process collective to checkpoint them)
    qkv = trainer.state.params["block0"]["attn"]["qkv"]["kernel"]
    assert not qkv.is_fully_addressable
    from pytorch_distributed_tpu.utils.checkpoint import gather_global

    param_l1 = float(
        sum(np.abs(np.asarray(leaf)).sum()
            for leaf in jax.tree.leaves(gather_global(trainer.state.params)))
    )
    # ---- sharded checkpoint: save + resume WITHOUT any full-state gather
    # anywhere. gather_global (the one full-materialization entry point) is
    # patched to raise so a regression to gather-based checkpointing fails
    # loudly on both ranks. (process_allgather itself can't be patched:
    # the save's own sync_global_devices barrier uses it for a tiny
    # name-agreement value — not state.)
    from pytorch_distributed_tpu.utils import checkpoint as ckpt_mod

    def _forbidden(*a, **k):
        raise AssertionError(
            "gather_global called during sharded checkpoint save/resume"
        )

    orig_allgather = ckpt_mod.gather_global
    ckpt_mod.gather_global = _forbidden
    try:
        trainer.ckpt.save_latest_sharded(trainer._payload_live(1, 5))
        import glob as _glob

        my_files = _glob.glob(os.path.join(
            save_dir, "latest.ckpt", f"shard-*-{get_rank():05d}.npz"
        ))
        assert my_files, f"no shard file for rank {get_rank()}"
        # the TP-sharded qkv stack's blocks span BOTH processes' files
        with open(os.path.join(save_dir, "latest.ckpt",
                               "manifest.json")) as f:
            manifest = json.load(f)
        qkv_meta = next(
            v for k, v in manifest["leaves"].items()
            if k.startswith("state/params") and "qkv/kernel" in k
        )
        qkv_files = {b["file"] for b in qkv_meta["blocks"]}
        assert len(qkv_files) == 2, qkv_files

        fresh = LMTrainer(model_cfg, train, val, cfg, mesh=mesh)
        assert fresh.try_resume()
        assert fresh.start_epoch == 1 and fresh.start_step == 5

        def _local_equal(a, b):
            # compare only this process's shards (the whole point is that
            # no process can see the global value of a sharded leaf)
            sa = {s.device.id: np.asarray(s.data)
                  for s in a.addressable_shards}
            sb = {s.device.id: np.asarray(s.data)
                  for s in b.addressable_shards}
            return sa.keys() == sb.keys() and all(
                np.array_equal(sa[k], sb[k]) for k in sa
            )

        same = jax.tree.map(_local_equal, trainer.state.params,
                            fresh.state.params)
        sharded_ckpt_ok = all(jax.tree.leaves(same))
    finally:
        ckpt_mod.gather_global = orig_allgather

    print(json.dumps({
        "rank": get_rank(),
        "world": get_world_size(),
        "val_loss": round(summary["loss"], 6),
        "ppl": round(summary["ppl"], 4),
        "best_acc": 0.0,
        "param_l1": param_l1,
        "final_step": int(jax.device_get(trainer.state.step)),
        "sharded_ckpt_ok": bool(sharded_ckpt_ok),
    }))


def _tiny_lm_trainer(save_dir: str):
    from pytorch_distributed_tpu.data import SyntheticTokens
    from pytorch_distributed_tpu.models.transformer import tiny_config
    from pytorch_distributed_tpu.parallel import make_mesh
    from pytorch_distributed_tpu.train import LMTrainer, LMTrainerConfig

    mesh = make_mesh(data_parallel=2, seq_parallel=2, model_parallel=2)
    model_cfg = tiny_config(
        attention="ring", model_axis="model", tp_size=2, dropout=0.0
    )
    cfg = LMTrainerConfig(epochs=1, batch_size=2, lr=1e-2, save_dir=save_dir,
                          num_workers=0, log_every=2)
    train = SyntheticTokens(size=16, seq_len=32, vocab_size=128)
    val = SyntheticTokens(size=8, seq_len=32, vocab_size=128, seed=9)
    return LMTrainer(model_cfg, train, val, cfg, mesh=mesh)


def run_lm_crash_save(save_dir: str) -> None:
    """Complete save (epoch 1, step 5), then a save that 'crashes' after
    its data files land but BEFORE the manifest commit (epoch 2, step 9).
    The parent relaunches with lm_crash_resume and asserts the survivor is
    the COMPLETE save — the durability property of token-named files."""
    from pytorch_distributed_tpu.parallel.distributed import get_rank
    from pytorch_distributed_tpu.utils.checkpoint import _ShardedSave

    trainer = _tiny_lm_trainer(save_dir)
    trainer.ckpt.save_latest_sharded(trainer._payload_live(1, 5))
    crash = _ShardedSave(trainer.ckpt.latest_path,
                         trainer._payload_live(2, 9))
    crash.write()  # both ranks' data files land...
    # ...and the job dies before finalize(): no barrier, no manifest
    print(json.dumps({"rank": get_rank(), "crash_save_done": True}))


def run_lm_crash_resume(save_dir: str) -> None:
    from pytorch_distributed_tpu.parallel.distributed import get_rank

    trainer = _tiny_lm_trainer(save_dir)
    resumed = trainer.try_resume()
    print(json.dumps({
        "rank": get_rank(),
        "resumed": bool(resumed),
        "epoch": int(trainer.start_epoch),
        "step": int(trainer.start_step),
    }))


def main() -> None:
    mode = sys.argv[1]
    save_dir = sys.argv[2]

    from pytorch_distributed_tpu.data.synthetic import SyntheticImageClassification
    from pytorch_distributed_tpu.models.resnet import BasicBlock, ResNet
    from pytorch_distributed_tpu.parallel import make_mesh
    from pytorch_distributed_tpu.parallel.distributed import (
        get_rank,
        get_world_size,
        init_process_group,
        is_primary,
    )
    from pytorch_distributed_tpu.train import Trainer, TrainerConfig
    from pytorch_distributed_tpu.utils.suspend import SuspendWatcher

    init_process_group()
    assert get_world_size() == 2, get_world_size()
    assert jax.device_count() == 8, jax.device_count()
    assert is_primary() == (get_rank() == 0)

    if mode == "lm":
        run_lm(save_dir)
        return
    if mode == "lm_crash_save":
        run_lm_crash_save(save_dir)
        return
    if mode == "lm_crash_resume":
        run_lm_crash_resume(save_dir)
        return

    model = ResNet(
        stage_sizes=(1, 1), block_cls=BasicBlock, num_classes=10, num_filters=8
    )
    epochs = 2 if mode == "train" else 50
    cfg = TrainerConfig(
        epochs=epochs,
        batch_size=4,
        lr=0.05,
        save_dir=save_dir,
        num_workers=0,
        log_every=1,
        suspend_sync_every=int(os.environ.get("SUSPEND_SYNC", "1")),
    )
    train_ds = SyntheticImageClassification(size=64, image_size=16, num_classes=10)
    val_ds = SyntheticImageClassification(size=16, image_size=16, num_classes=10, seed=1)

    watcher = SuspendWatcher(install_handlers=(mode == "suspend"))
    trainer = Trainer(
        model,
        train_ds,
        val_ds,
        cfg,
        mesh=make_mesh(),
        suspend_watcher=watcher,
        input_shape=(1, 16, 16, 3),
    )

    if mode == "suspend":
        # Signal readiness AFTER the first optimizer step has executed so the
        # parent's SIGTERM lands mid-training, not mid-compile.
        orig_epoch = trainer.train_epoch

        def epoch_with_sentinel(epoch, start_step=0):
            if epoch == trainer.start_epoch:
                first = [True]

                orig_suspend = trainer._maybe_suspend

                def hooked(ep, st):
                    if first[0]:
                        first[0] = False
                        with open(
                            os.path.join(save_dir, f"started.{get_rank()}"), "w"
                        ) as f:
                            f.write("1")
                    orig_suspend(ep, st)

                trainer._maybe_suspend = hooked
            return orig_epoch(epoch, start_step)

        trainer.train_epoch = epoch_with_sentinel

    summary = trainer.fit()
    param_l1 = float(
        sum(np.abs(np.asarray(jax.device_get(p))).sum()
            for p in jax.tree.leaves(trainer.state.params))
    )
    print(json.dumps({
        "rank": get_rank(),
        "world": get_world_size(),
        "resumed_from_step": trainer.start_epoch,
        "val_loss": round(summary["loss"], 6),
        "acc1": round(summary["acc1"], 4),
        "best_acc": round(summary["best_acc"], 4),
        "param_l1": param_l1,
        "final_step": int(jax.device_get(trainer.state.step)),
    }))


if __name__ == "__main__":
    main()
