"""init_process_group decision logic (VERDICT r1 weak #3: the auto-init
heuristics are load-bearing for pod launches — a wrong guess forks N
independent "primary" hosts that clobber each other's checkpoints — and had
never executed anywhere). The 2-process rendezvous itself is exercised for
real in tests/test_multihost.py; these pin the DECISION table by mocking
``jax.distributed.initialize``."""

import pytest

import pytorch_distributed_tpu.parallel.distributed as dist


@pytest.fixture()
def fresh(monkeypatch):
    """Reset the idempotency latch and capture initialize() calls."""
    calls = []

    def fake_initialize(*args, **kwargs):
        calls.append((args, kwargs))

    monkeypatch.setattr(dist, "_initialized", False)
    monkeypatch.setattr(dist.jax, "distributed", _FakeDistributed(fake_initialize))
    for var in ("MASTER_IP", "MASTER_PORT", "WORLD_SIZE", "RANK",
                "TPU_WORKER_HOSTNAMES", "MEGASCALE_COORDINATOR_ADDRESS"):
        monkeypatch.delenv(var, raising=False)
    return calls, monkeypatch


class _FakeDistributed:
    def __init__(self, initialize):
        self.initialize = initialize


def test_no_env_is_single_process_noop(fresh):
    calls, _ = fresh
    dist.init_process_group()
    assert calls == []
    assert dist._initialized is False


def test_reference_env_contract(fresh):
    """MASTER_IP/PORT + WORLD_SIZE/RANK (restnet_ddp.py:87-94 semantics:
    one process per host)."""
    calls, mp = fresh
    mp.setenv("MASTER_IP", "10.0.0.2")
    mp.setenv("MASTER_PORT", "29400")
    mp.setenv("WORLD_SIZE", "4")
    mp.setenv("RANK", "2")
    dist.init_process_group()
    assert len(calls) == 1
    _, kwargs = calls[0]
    assert kwargs == {
        "coordinator_address": "10.0.0.2:29400",
        "num_processes": 4,
        "process_id": 2,
    }
    assert dist._initialized is True
    # idempotent: a second call must not re-initialize
    dist.init_process_group()
    assert len(calls) == 1


def test_world_size_one_stays_single_process(fresh):
    calls, mp = fresh
    mp.setenv("MASTER_IP", "10.0.0.2")
    mp.setenv("MASTER_PORT", "29400")
    mp.setenv("WORLD_SIZE", "1")
    mp.setenv("RANK", "0")
    dist.init_process_group()
    assert calls == []


def test_explicit_args_override_env(fresh):
    calls, mp = fresh
    mp.setenv("WORLD_SIZE", "8")  # env says 8, explicit args win
    dist.init_process_group("1.2.3.4:1234", num_processes=2, process_id=1)
    assert calls == [((), {"coordinator_address": "1.2.3.4:1234",
                           "num_processes": 2, "process_id": 1})]


def test_tpu_pod_autodetect_multi_worker(fresh):
    """TPU_WORKER_HOSTNAMES with >1 workers → auto-init (pod metadata
    discovery); silently degrading would fork N independent primaries."""
    calls, mp = fresh
    mp.setenv("TPU_WORKER_HOSTNAMES", "t1k-worker-0,t1k-worker-1")
    dist.init_process_group()
    assert calls == [((), {})]  # full auto-discovery form
    assert dist._initialized is True


def test_single_worker_tunnel_stays_local(fresh):
    """A tunneled dev chip advertising TPU_WORKER_HOSTNAMES=localhost must
    NOT try to rendezvous."""
    calls, mp = fresh
    mp.setenv("TPU_WORKER_HOSTNAMES", "localhost")
    dist.init_process_group()
    assert calls == []


def test_megascale_autodetect(fresh):
    calls, mp = fresh
    mp.setenv("MEGASCALE_COORDINATOR_ADDRESS", "10.0.0.9:8476")
    dist.init_process_group()
    assert calls == [((), {})]
