"""Raw pre-decoded record path (data.raw): the decode-free input pipeline,
its uint8 contract, and device-side normalization parity (VERDICT r1
missing #2)."""

import io
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_tpu.data.loader import DataLoader
from pytorch_distributed_tpu.data.raw import (
    RawImageNet,
    decode_raw_record,
    encode_raw_record,
    write_imagenet_raw_split,
)
from pytorch_distributed_tpu.data.transforms import IMAGENET_MEAN, IMAGENET_STD


def make_split(tmp_path, n=12, size=64, split="train"):
    rng = np.random.default_rng(0)
    path = os.fspath(tmp_path / f"{split}.rawtprc")
    imgs = [rng.integers(0, 255, (size, size, 3)).astype(np.uint8) for _ in range(n)]
    write_imagenet_raw_split(path, ((im, i % 5) for i, im in enumerate(imgs)),
                             image_size=size)
    return path, imgs


def test_raw_record_roundtrip():
    img = np.arange(2 * 3 * 3, dtype=np.uint8).reshape(2, 3, 3)
    arr, label = decode_raw_record(encode_raw_record(img, 7))
    assert label == 7
    np.testing.assert_array_equal(arr, img)


def test_raw_split_roundtrip_and_eval_identity(tmp_path):
    _, imgs = make_split(tmp_path, split="val", size=48)
    ds = RawImageNet("val", data_dir=os.fspath(tmp_path), crop_size=48)
    assert len(ds) == 12
    for i in (0, 5, 11):
        arr, label = ds[i]
        assert arr.dtype == np.uint8 and arr.shape == (48, 48, 3)
        assert label == i % 5
        # eval aug at stored size is the identity: stored pixels verbatim
        np.testing.assert_array_equal(arr, imgs[i])


def test_raw_split_accepts_jpeg_bytes(tmp_path):
    from PIL import Image

    rng = np.random.default_rng(1)
    img = rng.integers(0, 255, (80, 100, 3)).astype(np.uint8)
    buf = io.BytesIO()
    Image.fromarray(img).save(buf, "JPEG", quality=95)
    path = os.fspath(tmp_path / "train.rawtprc")
    write_imagenet_raw_split(path, [(buf.getvalue(), 3)], image_size=64)
    arr, label = decode_raw_record(
        RawImageNet("train", data_dir=os.fspath(tmp_path)).reader.read(0)
    )
    assert arr.shape == (64, 64, 3) and label == 3  # short side 64, square crop


@pytest.mark.parametrize("aug", ["rrc", "crop"])
def test_raw_augmentation_deterministic_under_rng(tmp_path, aug):
    make_split(tmp_path, size=64)
    ds = RawImageNet("train", data_dir=os.fspath(tmp_path), crop_size=32, aug=aug)
    a1, _ = ds.getitem_rng(4, np.random.default_rng([1, 2, 4]))
    a2, _ = ds.getitem_rng(4, np.random.default_rng([1, 2, 4]))
    b, _ = ds.getitem_rng(4, np.random.default_rng([1, 3, 4]))
    np.testing.assert_array_equal(a1, a2)
    assert a1.shape == (32, 32, 3) and a1.dtype == np.uint8
    assert not np.array_equal(a1, b)  # different rng -> different crop


def test_loader_preserves_uint8(tmp_path):
    make_split(tmp_path, size=64)
    ds = RawImageNet("train", data_dir=os.fspath(tmp_path), crop_size=32, aug="crop")
    batch = next(iter(DataLoader(ds, batch_size=4, num_workers=0)))
    assert batch["image"].dtype == np.uint8
    assert batch["image"].shape == (4, 32, 32, 3)
    assert batch["label"].dtype == np.int32


def test_device_normalization_matches_host(tmp_path):
    """uint8 batch through the compiled step == host-normalized f32 batch:
    same loss, same grads-driven param update."""
    from pytorch_distributed_tpu.models.resnet import BasicBlock, ResNet
    from pytorch_distributed_tpu.ops.optim import sgd_with_weight_decay
    from pytorch_distributed_tpu.parallel import (
        replicated_sharding,
        shard_batch,
        single_device_mesh,
    )
    from pytorch_distributed_tpu.train.state import TrainState
    from pytorch_distributed_tpu.train.step import make_train_step, prepare_image

    rng = np.random.default_rng(2)
    u8 = rng.integers(0, 255, (8, 16, 16, 3)).astype(np.uint8)
    host_norm = (u8.astype(np.float32) / 255.0 - IMAGENET_MEAN) / IMAGENET_STD

    # unit parity of the device-side math itself
    np.testing.assert_allclose(
        np.asarray(prepare_image(jnp.asarray(u8))), host_norm, rtol=1e-6, atol=1e-6
    )

    model = ResNet(stage_sizes=(1, 1), block_cls=BasicBlock, num_classes=10,
                   num_filters=8)
    mesh = single_device_mesh()
    tx = sgd_with_weight_decay(0.1, momentum=0.9)
    labels = rng.integers(0, 10, 8).astype(np.int32)

    def one_step(images):
        state = TrainState.create(model, tx, jax.random.key(0), (1, 16, 16, 3))
        state = jax.device_put(state, replicated_sharding(mesh))
        step = make_train_step(mesh)
        state, metrics = step(state, shard_batch(mesh, {"image": images,
                                                        "label": labels}))
        return float(metrics["loss"]), jax.device_get(state.params)

    loss_u8, params_u8 = one_step(u8)
    loss_f32, params_f32 = one_step(host_norm)
    assert loss_u8 == pytest.approx(loss_f32, rel=1e-5)
    for (p1, a), (p2, b) in zip(
        jax.tree_util.tree_leaves_with_path(params_u8),
        jax.tree_util.tree_leaves_with_path(params_f32),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4,
                                   atol=1e-6, err_msg=str(p1))


def test_native_collate_batch_matches_python_path(tmp_path):
    """The C tpr_crop_batch fast path must be bit-identical to the Python
    per-sample path — same crops, flips, labels — for train and eval augs."""
    from pytorch_distributed_tpu.data import native

    if not native.available():
        pytest.skip("no native toolchain")
    make_split(tmp_path, n=16, size=64)
    for aug in ("crop", "none"):
        ds_n = RawImageNet("train", data_dir=os.fspath(tmp_path), crop_size=32,
                           aug=aug)
        ds_p = RawImageNet("train", data_dir=os.fspath(tmp_path), crop_size=32,
                           aug=aug, use_native=False)
        assert ds_n.reader._native is not None
        loader_n = DataLoader(ds_n, batch_size=8, num_workers=0, seed=3)
        loader_p = DataLoader(ds_p, batch_size=8, num_workers=0, seed=3)
        for bn, bp in zip(loader_n.iter_batches(0), loader_p.iter_batches(0)):
            assert bn["image"].dtype == np.uint8
            np.testing.assert_array_equal(bn["image"], bp["image"])
            np.testing.assert_array_equal(bn["label"], bp["label"])


def test_native_collate_falls_back_for_rrc_and_crc(tmp_path):
    """collate_batch must decline (return None) when the aug needs PIL or
    when per-read CRC verification was requested (the C kernel doesn't
    verify) — the loader then takes the per-sample path."""
    make_split(tmp_path, n=8, size=64)
    mk = lambda i: np.random.default_rng(i)
    ds = RawImageNet("train", data_dir=os.fspath(tmp_path), crop_size=32,
                     aug="rrc")
    assert ds.collate_batch([0, 1], mk) is None
    batch = next(iter(DataLoader(ds, batch_size=4, num_workers=0)))
    assert batch["image"].shape == (4, 32, 32, 3)
    ds_crc = RawImageNet("train", data_dir=os.fspath(tmp_path), crop_size=32,
                         aug="crop", verify_crc=True)
    assert ds_crc.collate_batch([0, 1], mk) is None
    # stored image smaller than the crop: Python degrades gracefully
    # (no-crop slice), the C kernel would bounds-error — decline instead
    ds_small = RawImageNet("train", data_dir=os.fspath(tmp_path),
                           crop_size=128, aug="crop")
    assert ds_small.collate_batch([0, 1], mk) is None


def test_native_collate_falls_back_for_variable_sizes(tmp_path):
    """A split with per-record sizes must not silently crop with record 0's
    dims: the C kernel rejects the mismatch and the per-sample path (which
    reads true sizes) serves the batch."""
    from pytorch_distributed_tpu.data import native
    from pytorch_distributed_tpu.data.packed_record import PackedRecordWriter

    if not native.available():
        pytest.skip("no native toolchain")
    rng = np.random.default_rng(0)
    path = os.fspath(tmp_path / "train.rawtprc")
    with PackedRecordWriter(path) as w:
        for i, size in enumerate((64, 48, 64, 96)):
            img = rng.integers(0, 255, (size, size, 3)).astype(np.uint8)
            w.write(encode_raw_record(img, i))
    ds = RawImageNet("train", data_dir=os.fspath(tmp_path), crop_size=32,
                     aug="crop")
    assert ds.collate_batch([0, 1, 2, 3],
                            lambda i: np.random.default_rng(i)) is None
    batch = next(iter(DataLoader(ds, batch_size=4, num_workers=0, seed=5)))
    assert batch["image"].shape == (4, 32, 32, 3)
    # and the per-sample path's samples match direct dataset access
    a, _ = ds.getitem_rng(1, np.random.default_rng([5, 0, 1]))
    np.testing.assert_array_equal(batch["image"][1], a)


def test_custom_collate_fn_disables_fast_path(tmp_path):
    make_split(tmp_path, n=8, size=64)
    ds = RawImageNet("train", data_dir=os.fspath(tmp_path), crop_size=32,
                     aug="crop")
    calls = []

    def my_collate(samples):
        calls.append(len(samples))
        images = np.stack([s[0] for s in samples])
        return {"image": images, "label": np.zeros(len(samples), np.int32),
                "extra": True}

    batch = next(iter(DataLoader(ds, batch_size=4, num_workers=0,
                                 collate_fn=my_collate)))
    assert calls and batch["extra"] is True


def test_native_crop_batch_bounds_check(tmp_path):
    from pytorch_distributed_tpu.data import native

    if not native.available():
        pytest.skip("no native toolchain")
    path, _ = make_split(tmp_path, n=4, size=32)
    from pytorch_distributed_tpu.data.packed_record import PackedRecordReader

    r = PackedRecordReader(path)
    with pytest.raises(IOError):
        r._native.crop_batch([0], [30], [0], [False], 16, 32, 32)  # top+crop > h
    with pytest.raises(IOError):
        r._native.crop_batch([99], [0], [0], [False], 16, 32, 32)  # bad index
    with pytest.raises(native.SizeMismatch):
        r._native.crop_batch([0], [0], [0], [False], 16, 64, 64)
