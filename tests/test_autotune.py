"""Cost-card autotuner (round 20 tentpole d): sweep → persist → reload
keyed by fingerprint. The contract under test: the tuned file's key
excludes the knobs being tuned (an engine can find it BEFORE choosing
block_len/split_s), a matching engine loads it with zero new jit-cache
entries and full registry coverage, and every miss mode — stale
fingerprint, corrupt file, absent directory — is a clean default-config
construction, never a crash."""

import dataclasses
import json

import pytest

import jax
import jax.numpy as jnp
import numpy as np

from pytorch_distributed_tpu.analysis import no_recompile
from pytorch_distributed_tpu.compilecache import serving_registry
from pytorch_distributed_tpu.models.transformer import (
    TransformerLM,
    tiny_config,
)
from pytorch_distributed_tpu.serving.engine import ChunkJob, PagedEngine
from pytorch_distributed_tpu.telemetry.autotune import (
    TunedConfig,
    autotune_fingerprint,
    load_tuned,
    save_tuned,
    sweep,
    tuned_path,
)


def setup(max_seq_len=64, **over):
    cfg = tiny_config(attention="dense", max_seq_len=max_seq_len, **over)
    params = TransformerLM(cfg).init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return cfg, params


def _serve_cycle(eng, prompt_len=8, ticks=3):
    rng = np.random.default_rng(0)
    prompt = rng.integers(1, eng.config.vocab_size,
                          prompt_len).astype(np.int32)
    assert eng.admit(0, prompt_len, ticks + 1)
    for start in range(0, prompt_len, eng.chunk):
        seg = prompt[start:start + eng.chunk]
        toks = np.zeros((eng.chunk,), np.int32)
        toks[:len(seg)] = seg
        last = start + eng.chunk >= prompt_len
        eng.run_chunks([ChunkJob(
            slot=0, tokens=toks, start=start, is_last=last,
            last_idx=(prompt_len - 1 - start) if last else 0,
        )])
    pos = np.zeros(eng.n_slots, np.int32)
    pos[0] = prompt_len
    act = np.zeros(eng.n_slots, bool)
    act[0] = True
    key = jax.random.key(1)
    for _ in range(ticks):
        _t, pos = eng.decode(pos, act, key)


def test_fingerprint_excludes_tuned_knobs():
    """The keying rule: the tuned parameters must not key their own
    file. Configs differing only in split_s map to ONE fingerprint;
    anything that changes the program family (kv_dtype, gather_impl,
    n_slots) maps to a different one."""
    cfg, _ = setup()
    base = autotune_fingerprint(cfg, 2, kv_dtype=None)
    assert autotune_fingerprint(
        dataclasses.replace(cfg, split_s=4), 2, kv_dtype=None
    ) == base
    assert autotune_fingerprint(cfg, 2, kv_dtype="fp8") != base
    assert autotune_fingerprint(cfg, 4, kv_dtype=None) != base
    assert autotune_fingerprint(
        dataclasses.replace(cfg, gather_impl="pallas"), 2, kv_dtype=None
    ) != base


@pytest.mark.slow
def test_sweep_round_trip_engine_loads_tuned(tmp_path):
    """THE acceptance loop: sweep two candidates → winner persisted →
    a fresh same-shape engine loads it (tuned knobs applied, provenance
    says so), serves, and its registry covers every compiled program
    with the decode tick no_recompile-clean after warmup."""
    cfg, params = setup()
    out = str(tmp_path)
    tuned = sweep(
        cfg, params, 2, block_lens=(8, 16), prefill_chunks=(8,),
        split_ss=(1,), gather_impl="pallas", prompt_len=8, ticks=2,
        out_dir=out,
    )
    assert tuned.backend == jax.default_backend()
    assert len(tuned.candidates) == 2
    # file round-trips bit-for-bit through the loader
    again = load_tuned(out, tuned.fingerprint)
    assert again == tuned

    eng = PagedEngine(cfg, params, 2, gather_impl="pallas",
                      autotune_dir=out)
    assert eng.tuned is not None
    assert eng.block_len == tuned.block_len
    assert eng.chunk == tuned.prefill_chunk
    assert eng.config.split_s == tuned.split_s
    prov = eng.tuned_provenance()
    assert prov["tuned"] and prov["tuned_match"]
    assert prov["tuned_fingerprint"] == tuned.fingerprint

    _serve_cycle(eng)
    serving_registry(eng).assert_covers(eng.compiled_program_names())
    # decode is warm: wrapping it in the guard and ticking further must
    # add zero jit-cache entries (the tuned config compiled exactly the
    # predicted programs, nothing drifts per tick)
    eng._decode_fn = no_recompile(eng._decode(), warmup_steps=1)
    pos = np.full(2, 11, np.int32)
    act = np.array([True, False])
    key = jax.random.key(2)
    for _ in range(3):
        _t, pos = eng.decode(pos, act, key)
    assert eng._decode_fn.stats.recompiles_after_warmup == 0


def test_stale_fingerprint_is_clean_miss(tmp_path):
    """A tuned file from ANOTHER environment/shape must not load: the
    engine constructs with defaults, flags tuned_match False, and
    nothing raises."""
    cfg, params = setup()
    out = str(tmp_path)
    fp = autotune_fingerprint(cfg, 2, kv_dtype=None)
    save_tuned(out, TunedConfig(
        block_len=8, prefill_chunk=8, split_s=2, fingerprint=fp,
        backend="cpu", decode_tok_s=1.0,
    ))
    # direct loader: wrong fingerprint → None
    assert load_tuned(out, "0" * 16) is None
    # engine with a DIFFERENT shape (n_slots) keys a different
    # fingerprint → clean miss, defaults kept
    eng = PagedEngine(cfg, params, 4, autotune_dir=out)
    assert eng.tuned is None
    assert eng.block_len == 16 and eng.chunk == 128
    prov = eng.tuned_provenance()
    assert prov["tuned"] is False and prov["tuned_match"] is False
    # matching shape → hit (the file above was keyed for n_slots=2)
    hit = PagedEngine(cfg, params, 2, autotune_dir=out)
    assert hit.tuned is not None and hit.block_len == 8


def test_corrupt_and_absent_files_are_clean_misses(tmp_path):
    cfg, params = setup()
    out = str(tmp_path)
    fp = autotune_fingerprint(cfg, 2, kv_dtype=None)
    # absent dir / absent file
    assert load_tuned(str(tmp_path / "nope"), fp) is None
    assert load_tuned(out, fp) is None
    # torn/corrupt JSON
    with open(tuned_path(out, fp), "w") as f:
        f.write('{"block_len": 8, "prefill_ch')
    assert load_tuned(out, fp) is None
    # parseable but missing required fields
    with open(tuned_path(out, fp), "w") as f:
        json.dump({"fingerprint": fp}, f)
    assert load_tuned(out, fp) is None
    eng = PagedEngine(cfg, params, 2, autotune_dir=out)
    assert eng.tuned is None  # corrupt file: default engine, no crash


def test_explicit_args_win_over_tuned(tmp_path):
    """A caller who PASSES block_len/split_s gets those values even when
    a tuned file matches — the file fills in defaults, it does not
    override explicit choices."""
    cfg, params = setup()
    out = str(tmp_path)
    fp = autotune_fingerprint(cfg, 2, kv_dtype=None)
    save_tuned(out, TunedConfig(
        block_len=8, prefill_chunk=16, split_s=2, fingerprint=fp,
        backend="cpu", decode_tok_s=1.0,
    ))
    eng = PagedEngine(cfg, params, 2, block_len=32, prefill_chunk=64,
                      split_s=1, autotune_dir=out)
    assert eng.tuned is not None  # the file DID match...
    assert eng.block_len == 32  # ...but explicit arguments held
    assert eng.chunk == 64
    assert eng.config.split_s == 1
