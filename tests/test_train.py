"""Trainer behavior: loss goes down, checkpoint roundtrip, suspend/resume
bit-parity, BN-stat semantics, fp16 dynamic-scaler path.

The suspend/resume test is the one SURVEY.md §4 calls for: inject the
suspend signal at step N, "relaunch", and assert the resumed run's final
state equals an uninterrupted run's — stronger than anything the reference
could test (it has no tests at all).
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_tpu.data import SyntheticImageClassification
from pytorch_distributed_tpu.models.resnet import BasicBlock, ResNet
from pytorch_distributed_tpu.parallel import make_mesh
from pytorch_distributed_tpu.train import Trainer, TrainerConfig
from conftest import FireAtStep  # noqa: E402


def tiny_model(**kw):
    return ResNet(
        stage_sizes=(1, 1), block_cls=BasicBlock, num_classes=10, num_filters=8, **kw
    )


def make_trainer(tmp_path, devices8, watcher=None, epochs=2, precision="fp32",
                 val_size=32, **cfg_over):
    """Tiny ResNet trainer on the 8-device mesh; ``cfg_over`` forwards any
    extra TrainerConfig field (used by test_resilience for the guard/
    watchdog/interval-save knobs)."""
    train_ds = SyntheticImageClassification(size=128, image_size=16, num_classes=10)
    val_ds = SyntheticImageClassification(
        size=val_size, image_size=16, num_classes=10, seed=1
    )
    cfg = TrainerConfig(
        epochs=epochs,
        batch_size=2,  # ×8 replicas = global 16 → 8 steps/epoch
        lr=0.05,
        precision=precision,
        save_dir=os.fspath(tmp_path),
        log_every=0,
        num_workers=0,
        prefetch=1,
        **cfg_over,
    )
    return Trainer(
        tiny_model(dtype=jnp.bfloat16 if precision == "bf16" else jnp.float32),
        train_ds,
        val_ds,
        cfg,
        mesh=make_mesh(devices8),
        suspend_watcher=watcher,
        input_shape=(1, 16, 16, 3),
    )


def params_equal(a, b, **tol):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), **tol)


def test_fit_loss_decreases_and_best_tracking(tmp_path, devices8):
    trainer = make_trainer(tmp_path, devices8)
    m0 = trainer.validate()
    out = trainer.fit()
    assert out["loss"] < m0["loss"]
    assert out["best_acc"] > 0
    assert os.path.exists(trainer.ckpt.best_path)  # ref restnet_ddp.py:145-150
    assert not trainer.ckpt.has_latest()  # latest only written on suspend


def test_validate_partial_batch_smaller_than_pad(tmp_path, devices8):
    """Final val batch of 3 rows on 8 replicas: pad (5) exceeds the batch —
    wrap-pad must tile, not truncate. Counts include duplicates, matching
    torch DistributedSampler's non-drop_last padding (restnet_ddp.py:118)."""
    trainer = make_trainer(tmp_path, devices8, val_size=35)  # 16+16+3
    out = trainer.validate()
    assert out["count"] == 40.0  # 16 + 16 + (3 wrapped to 8)


def test_suspend_resume_bit_parity(tmp_path, devices8):
    # Uninterrupted reference run.
    t_ref = make_trainer(tmp_path / "ref", devices8)
    t_ref.fit()

    # Interrupted run: suspend fires mid-epoch-1 (poll 11 → epoch 1, step 2).
    t_int = make_trainer(tmp_path / "int", devices8, watcher=FireAtStep(11))
    with pytest.raises(SystemExit):
        t_int.fit()
    assert t_int.ckpt.has_latest()

    # "Relaunch": fresh trainer, same save dir → resumes and finishes.
    t_res = make_trainer(tmp_path / "int", devices8)
    assert t_res.try_resume()
    assert (t_res.start_epoch, t_res.start_step) == (1, 3)
    t_res2 = make_trainer(tmp_path / "int", devices8)
    t_res2.fit()

    params_equal(t_ref.state.params, t_res2.state.params, rtol=0, atol=0)
    params_equal(t_ref.state.batch_stats, t_res2.state.batch_stats, rtol=0, atol=0)
    assert int(t_ref.state.step) == int(t_res2.state.step)


def test_checkpoint_roundtrip(tmp_path, devices8):
    trainer = make_trainer(tmp_path, devices8)
    trainer.best_acc = 42.0
    trainer.ckpt.save_latest(trainer._payload(3, 5))

    fresh = make_trainer(tmp_path, devices8)
    assert fresh.try_resume()
    assert (fresh.start_epoch, fresh.start_step) == (3, 5)
    assert fresh.best_acc == 42.0
    params_equal(fresh.state.params, trainer.state.params, rtol=0, atol=0)
    # restored state is mesh-placed and usable
    fresh.train_epoch(3, start_step=7)


def test_bn_stats_are_cross_replica_mean(devices8):
    """Training BN normalizes per replica (DDP parity) but running stats are
    pmean'd — verify against a hand-computed update."""
    from pytorch_distributed_tpu.ops.optim import sgd_with_weight_decay
    from pytorch_distributed_tpu.parallel import replicated_sharding, shard_batch
    from pytorch_distributed_tpu.train.state import TrainState
    from pytorch_distributed_tpu.train.step import make_train_step

    mesh = make_mesh(devices8)
    model = tiny_model()
    tx = sgd_with_weight_decay(0.0, momentum=0.0, weight_decay=0.0)
    state = TrainState.create(model, tx, jax.random.key(0), (1, 16, 16, 3))
    state = jax.device_put(state, replicated_sharding(mesh))

    rng = np.random.default_rng(0)
    images = rng.normal(size=(16, 16, 16, 3)).astype(np.float32)
    batch = shard_batch(
        mesh, {"image": images, "label": np.zeros(16, np.int32)}
    )
    old_mean = np.asarray(state.batch_stats["bn_init"]["mean"])
    stem_kernel = np.asarray(jax.device_get(state.params["conv_init"]["kernel"]))
    new_state, _ = make_train_step(mesh)(state, batch)
    got = np.asarray(new_state.batch_stats["bn_init"]["mean"])

    # Expected: momentum-0.9 EMA toward the mean over replicas of each
    # replica's post-stem-conv batch mean (== global mean for equal shards).
    stem = jax.lax.conv_general_dilated(
        images,
        stem_kernel,
        window_strides=(2, 2),
        padding=[(3, 3), (3, 3)],
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    per_replica_means = stem.reshape(8, 2, *stem.shape[1:]).mean(axis=(1, 2, 3))
    expected = 0.9 * old_mean + 0.1 * per_replica_means.mean(axis=0)
    np.testing.assert_allclose(got, expected, rtol=1e-4, atol=1e-5)


def test_fp16_dynamic_scaler_skips_nonfinite(devices8):
    """GradScaler contract (resnet_ddp_apex.py:30-33): a non-finite gradient
    skips the update and halves the scale."""
    from pytorch_distributed_tpu.ops.optim import sgd_with_weight_decay
    from pytorch_distributed_tpu.ops.precision import DynamicLossScaler
    from pytorch_distributed_tpu.parallel import replicated_sharding, shard_batch
    from pytorch_distributed_tpu.train.state import TrainState
    from pytorch_distributed_tpu.train.step import make_train_step

    mesh = make_mesh(devices8)
    model = tiny_model()
    tx = sgd_with_weight_decay(0.05)
    state = TrainState.create(
        model,
        tx,
        jax.random.key(0),
        (1, 16, 16, 3),
        scaler=DynamicLossScaler.create(init_scale=16.0),
    )
    state = jax.device_put(state, replicated_sharding(mesh))
    step_fn = make_train_step(mesh)

    rng = np.random.default_rng(0)
    good = {
        "image": rng.normal(size=(16, 16, 16, 3)).astype(np.float32),
        "label": np.zeros(16, np.int32),
    }
    bad = {"image": np.full((16, 16, 16, 3), np.nan, np.float32),
           "label": np.zeros(16, np.int32)}

    p0 = jax.device_get(state.params)
    state, metrics = step_fn(state, shard_batch(mesh, bad))
    assert float(metrics["grads_finite"]) == 0.0
    assert float(state.scaler.scale) == 8.0  # backed off
    params_equal(state.params, p0, rtol=0, atol=0)  # update skipped

    state, metrics = step_fn(state, shard_batch(mesh, good))
    assert float(metrics["grads_finite"]) == 1.0
    changed = any(
        not np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree.leaves(state.params), jax.tree.leaves(p0))
    )
    assert changed
