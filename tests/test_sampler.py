"""DistributedSampler parity vs torch.utils.data.DistributedSampler."""

import numpy as np
import pytest
import torch.utils.data

from pytorch_distributed_tpu.data import DistributedSampler


class _FakeDataset:
    def __init__(self, n):
        self.n = n

    def __len__(self):
        return self.n


@pytest.mark.parametrize("size,replicas", [(100, 8), (101, 8), (7, 4), (16, 1)])
def test_shard_sizes_match_torch(size, replicas):
    for rank in range(replicas):
        ours = DistributedSampler(size, replicas, rank, shuffle=False)
        theirs = torch.utils.data.DistributedSampler(
            _FakeDataset(size), num_replicas=replicas, rank=rank, shuffle=False
        )
        assert len(ours) == len(theirs)
        assert list(ours) == list(theirs)  # unshuffled order is identical


@pytest.mark.parametrize("size,replicas", [(100, 8), (103, 8)])
def test_drop_last_matches_torch(size, replicas):
    for rank in range(replicas):
        ours = DistributedSampler(size, replicas, rank, shuffle=False, drop_last=True)
        theirs = torch.utils.data.DistributedSampler(
            _FakeDataset(size),
            num_replicas=replicas,
            rank=rank,
            shuffle=False,
            drop_last=True,
        )
        assert len(ours) == len(theirs)
        assert list(ours) == list(theirs)


def test_shuffled_shards_partition_with_padding():
    # Shuffled: our RNG differs from torch's by design, but the invariants
    # torch guarantees must hold: shards are disjoint (mod padding), cover
    # the dataset, and all replicas use the same permutation.
    size, replicas = 101, 8
    samplers = [DistributedSampler(size, replicas, r, seed=1) for r in range(replicas)]
    for s in samplers:
        s.set_epoch(3)
    shards = [np.asarray(list(s)) for s in samplers]
    allidx = np.concatenate(shards)
    assert len(allidx) == samplers[0].total_size
    # covers every dataset index at least once
    assert set(allidx.tolist()) == set(range(size))
    # padded total: exactly total_size - size duplicates
    assert len(allidx) - len(set(allidx.tolist())) == samplers[0].total_size - size


def test_epoch_reshuffle_changes_order_deterministically():
    s = DistributedSampler(64, 4, 0, seed=7)
    s.set_epoch(0)
    e0 = list(s)
    s.set_epoch(1)
    e1 = list(s)
    s.set_epoch(0)
    assert list(s) == e0  # same epoch → same order (resume invariant)
    assert e0 != e1


def test_iter_from_seeks_without_io():
    s = DistributedSampler(100, 4, 2, seed=3)
    s.set_epoch(5)
    full = list(s)
    assert list(s.iter_from(10)) == full[10:]
    assert list(s.iter_from(0)) == full


def test_rank_validation():
    with pytest.raises(ValueError):
        DistributedSampler(10, 4, 4)


def test_local_padding_mask_marks_wrapped_duplicates():
    """The wrap-padding positions (torch repeats indices to reach a
    divisible total) are exactly the ones the mask flags, on every rank;
    unpadded and drop_last samplers have all-False masks."""
    from pytorch_distributed_tpu.data.sampler import DistributedSampler

    size, replicas = 10, 4  # total_size 12, 2 padded positions
    seen = []
    for rank in range(replicas):
        s = DistributedSampler(size, replicas, rank, shuffle=True, seed=3)
        mask = s.local_padding_mask()
        idx = s.local_indices()
        assert mask.shape == idx.shape
        seen.append((idx, mask))
    total_pad = sum(m.sum() for _, m in seen)
    assert total_pad == 2
    # every dataset index appears exactly once among unpadded positions
    real = np.concatenate([i[~m] for i, m in seen])
    assert sorted(real.tolist()) == list(range(size))
    # padded positions duplicate indices that already appear unpadded
    dup = np.concatenate([i[m] for i, m in seen])
    assert set(dup.tolist()) <= set(real.tolist())

    even = DistributedSampler(12, 4, 0)
    assert not even.local_padding_mask().any()
    dropped = DistributedSampler(10, 4, 1, drop_last=True)
    assert not dropped.local_padding_mask().any()
