"""Grouped-query attention (round 4): K/V carry num_kv_heads heads shared
by groups of query heads — the Llama-family serving trade. The cache and
kv projection shrink by the group factor; compute repeats K/V to full
heads, so every attention path downstream is plain MHA. These pin the
shapes, the training path, decode parity, and TP composition."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

pytestmark = pytest.mark.slow

from pytorch_distributed_tpu.models.generate import generate, init_cache
from pytorch_distributed_tpu.models.transformer import (
    TransformerLM,
    tiny_config,
)
from pytorch_distributed_tpu.ops.optim import sgd_with_weight_decay
from pytorch_distributed_tpu.parallel import make_mesh
from pytorch_distributed_tpu.train.lm import (
    create_lm_state,
    make_lm_train_step,
    shard_lm_state,
    shift_labels,
)
from pytorch_distributed_tpu.train.lm_trainer import shard_lm_batch


def test_gqa_config_validation():
    with pytest.raises(ValueError, match="num_kv_heads"):
        tiny_config(num_heads=4, embed_dim=32, num_kv_heads=3)
    with pytest.raises(ValueError, match="num_kv_heads"):
        tiny_config(num_heads=4, embed_dim=32, num_kv_heads=1,
                    model_axis="model", tp_size=2)
    with pytest.raises(ValueError, match="num_kv_heads must be >= 1"):
        tiny_config(num_heads=4, embed_dim=32, num_kv_heads=0)
    with pytest.raises(ValueError, match="num_kv_heads must be >= 1"):
        tiny_config(num_heads=4, embed_dim=32, num_kv_heads=-2)
    tiny_config(num_heads=4, embed_dim=32, num_kv_heads=2)  # fine


def test_gqa_param_tree_and_cache_shapes():
    cfg = tiny_config(num_heads=4, embed_dim=32, num_kv_heads=2,
                      max_seq_len=64)
    params = TransformerLM(cfg).init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    attn = params["block0"]["attn"]
    assert "qkv" not in attn
    assert attn["q"]["kernel"].shape == (32, 4, 8)
    assert attn["kv"]["kernel"].shape == (32, 2, 2, 8)  # 2 kv heads
    cache = init_cache(cfg, params, batch_size=3)
    k = cache["block0"]["attn"]["key"]
    assert k.shape == (3, 64, 2, 8)  # H_kv, not H: the memory win


def test_gqa_equals_mha_when_groups_are_one():
    """num_kv_heads == num_heads: same math as MHA up to the projection
    split — porting fused qkv weights into the split layout reproduces
    the fused model's logits exactly."""
    cfg_mha = tiny_config(num_heads=4, embed_dim=32, max_seq_len=64)
    cfg_gqa = dataclasses.replace(cfg_mha, num_kv_heads=4)
    params = TransformerLM(cfg_mha).init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]

    def split_qkv(p):
        import copy

        p = copy.deepcopy(jax.device_get(p))
        for name, blk in p.items():
            if not name.startswith("block"):
                continue
            qkv = blk["attn"].pop("qkv")
            blk["attn"]["q"] = {
                "kernel": qkv["kernel"][:, 0], "bias": qkv["bias"][0],
            }
            blk["attn"]["kv"] = {
                "kernel": qkv["kernel"][:, 1:], "bias": qkv["bias"][1:],
            }
        return p

    tokens = jnp.asarray(
        np.random.default_rng(0).integers(1, 128, (2, 16)), jnp.int32
    )
    out_mha = TransformerLM(cfg_mha).apply(
        {"params": params}, tokens, train=False
    )
    out_gqa = TransformerLM(cfg_gqa).apply(
        {"params": split_qkv(params)}, tokens, train=False
    )
    np.testing.assert_allclose(np.asarray(out_gqa), np.asarray(out_mha),
                               rtol=1e-5, atol=1e-5)


def test_gqa_decode_matches_full_forward():
    """Cached GQA decode == full-forward greedy rollout, token for token
    (the narrow cache + repeat-at-compute must not change the math)."""
    cfg = tiny_config(num_heads=4, embed_dim=32, num_kv_heads=2,
                      max_seq_len=64)
    model = TransformerLM(cfg)
    params = model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))[
        "params"]
    rng = np.random.default_rng(1)
    prompt = jnp.asarray(rng.integers(1, 128, (2, 7)), jnp.int32)

    got = np.asarray(generate(cfg, params, prompt, jax.random.key(2),
                              max_new_tokens=8, temperature=0.0))
    # manual rollout through the FULL forward (no cache)
    toks = np.asarray(prompt)
    for _ in range(8):
        logits = model.apply({"params": params}, jnp.asarray(toks),
                             train=False)
        nxt = np.argmax(np.asarray(logits)[:, -1], axis=-1).astype(np.int32)
        toks = np.concatenate([toks, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(got, toks)


def test_gqa_trains_under_ring_and_tp(devices8):
    """GQA through the real train step on a dp2 x sp2 x tp2 mesh (kv
    heads sharded over the model axis) matches the single-device run."""
    tx = sgd_with_weight_decay(0.1, momentum=0.9)

    def run(mesh, cfg, steps=3):
        state = create_lm_state(cfg, tx, jax.random.key(0), init_len=8)
        state, specs = shard_lm_state(mesh, state, cfg)
        step = make_lm_train_step(mesh, state_specs=specs, config=cfg)
        rng = np.random.default_rng(0)
        losses = []
        for i in range(steps):
            tokens = rng.integers(1, 128, (4, 32)).astype(np.int32)
            labels, weights = shift_labels(tokens)
            batch = shard_lm_batch(mesh, {
                "tokens": tokens, "labels": labels, "weights": weights,
            })
            state, m = step(state, batch)
            losses.append(float(m["loss"]))
        return state, losses

    mesh_tp = make_mesh(devices8, data_parallel=2, seq_parallel=2,
                        model_parallel=2)
    cfg_tp = tiny_config(num_heads=4, embed_dim=32, num_kv_heads=2,
                         attention="ring", model_axis="model", tp_size=2)
    mesh_1 = make_mesh(devices8[:1])
    cfg_1 = tiny_config(num_heads=4, embed_dim=32, num_kv_heads=2,
                        attention="dense")
    state_tp, losses_tp = run(mesh_tp, cfg_tp)
    state_1, losses_1 = run(mesh_1, cfg_1)
    np.testing.assert_allclose(losses_tp, losses_1, rtol=5e-4)
    flat_1 = {str(p): v for p, v in
              jax.tree_util.tree_leaves_with_path(state_1.params)}
    for path, leaf in jax.tree_util.tree_leaves_with_path(state_tp.params):
        np.testing.assert_allclose(
            np.asarray(leaf), np.asarray(flat_1[str(path)]),
            rtol=2e-3, atol=3e-5, err_msg=str(path),
        )
    # the kv projection actually learned (grads flowed through the
    # repeat); its kernel moved from init
    init = create_lm_state(cfg_1, tx, jax.random.key(0), init_len=8)
    moved = np.abs(
        np.asarray(state_1.params["block0"]["attn"]["kv"]["kernel"])
        - np.asarray(init.params["block0"]["attn"]["kv"]["kernel"])
    ).max()
    assert moved > 1e-4
