"""Async fleet host runtime (round 16 tentpole): dispatch-then-collect
token identity vs the synchronous loop (plain, disaggregated, and
pressure fleets), lagged-collect ordering, the early-collect protocol on
preempt/drain, the worker pool's barrier semantics, worker-thread host
marks in the bubble classifier, the ledger's collect-site completion,
the union busy rollup, the no-hot-sync + no_recompile guards with the
async loop armed, a SIGKILL-mid-swap async-loop kill-matrix cell, and a
rules_threads-clean gate on every module the refactor touched."""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_tpu.analysis import no_recompile
from pytorch_distributed_tpu.analysis.core import LintContext, parse_file
from pytorch_distributed_tpu.analysis.rules_threads import (
    check_threads,
    thread_inventory,
)
from pytorch_distributed_tpu.fleet import FleetRouter, SLOConfig
from pytorch_distributed_tpu.models.transformer import (
    TransformerLM,
    tiny_config,
)
from pytorch_distributed_tpu.resilience import faults
from pytorch_distributed_tpu.resilience.faults import FaultPlan, FaultSpec
from pytorch_distributed_tpu.serving import HostWorkerPool, Scheduler
from pytorch_distributed_tpu.telemetry import (
    DispatchLedger,
    ReqTracer,
    classify_bubbles,
    fleet_busy_summary,
    validate_stream,
)
from pytorch_distributed_tpu.utils.profiling import MetricsLogger

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SCHED_KW = dict(n_slots=3, block_len=8, prefill_chunk=16,
                admit_per_step=4)


@pytest.fixture(scope="module")
def model():
    cfg = tiny_config(attention="dense", max_seq_len=64)
    params = TransformerLM(cfg).init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return cfg, params


def _prompts(cfg, lens=(5, 16, 23, 31, 9, 17), seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(1, cfg.vocab_size, l).astype(np.int32)
            for l in lens]


def _fleet(cfg, params, async_host, **extra):
    kw = dict(SCHED_KW)
    kw.update(extra.pop("sched_kw", {}))
    return FleetRouter(
        cfg, params, n_replicas=2, async_host=async_host,
        slo=SLOConfig(spill_queue_depth=2, shed_queue_depth=10**6),
        **extra, **kw,
    )


# ---------------------------------------------------------------------------
# token identity: async vs sync, across fleet modes
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("mode", [
    pytest.param("plain", marks=pytest.mark.slow),
    "disagg",
    pytest.param("pressure", marks=pytest.mark.slow),
])
def test_async_sync_token_identity(model, mode):
    """Bit-identical greedy token streams between the synchronous loop
    and dispatch-then-collect, on the plain fleet, the disaggregated
    prefill/decode fleet, and the over-committed pressure fleet (where
    preempt/restore fires under the async loop too)."""
    cfg, params = model
    extra = {}
    if mode == "disagg":
        extra = dict(disaggregate=True, decode_slots=4,
                     handoffs_per_tick=1)
    elif mode == "pressure":
        extra = dict(offload=True, preempt_on_oom=True,
                     swap_policy="swap", protect_ticks=0,
                     sched_kw=dict(n_blocks=10))
    results = {}
    for async_host in (False, True):
        r = _fleet(cfg, params, async_host, **extra)
        for i, p in enumerate(_prompts(cfg)):
            r.submit(p, 5, session=i % 3)
        results[async_host] = (r.drain(), r)
    sync_out, _ = results[False]
    async_out, ra = results[True]
    assert set(sync_out) == set(async_out)
    for rid in sync_out:
        assert sync_out[rid] == async_out[rid], f"stream {rid} diverged"
    assert not ra.rejected
    if mode == "pressure":
        assert ra.metrics()["preempts"] >= 1
        assert ra.metrics()["restores"] >= 1
    if mode == "disagg":
        assert ra.metrics()["handoffs"] == len(sync_out)
    # every pool block freed, worker pool drained
    for s in ra.replicas:
        assert s.engine.allocator.in_use == 0
        assert not s.has_uncollected


@pytest.mark.slow
def test_async_identity_on_bursty_trace(model):
    """The smoke-trace identity gate: a seeded bursty trace replayed
    through both loops at the same per-tick load — same served rid set,
    same token values."""
    from pytorch_distributed_tpu.fleet import (
        clamp_trace,
        generate_trace,
        prompt_for,
        replay_trace,
    )

    cfg, params = model
    trace = clamp_trace(
        generate_trace(seed=5, duration_s=30.0, base_rate=0.6,
                       sessions=8, prompt_max=48, max_new_max=8),
        cfg.max_seq_len, SCHED_KW["prefill_chunk"],
    )
    outs = {}
    for async_host in (False, True):
        r = _fleet(cfg, params, async_host)
        replay_trace(
            trace,
            lambda req: r.submit(prompt_for(req, cfg.vocab_size),
                                 req.max_new, session=req.session),
            r.step,
            lambda: r.idle,
        )
        outs[async_host] = dict(r.results)
    assert outs[False] == outs[True]


# ---------------------------------------------------------------------------
# lagged-collect ordering
# ---------------------------------------------------------------------------


def test_lagged_collect_one_tick_behind(model):
    """The async loop's contract: ``step()`` N returns the tokens of
    tick N−1 (collected lagged) while tick N is left in flight — a
    pending, uncollected ``TickHandle`` exists between steps, and the
    per-rid stream order is preserved."""
    cfg, params = model
    r = _fleet(cfg, params, True)
    rid = r.submit(np.arange(1, 10, dtype=np.int32), 3)
    first_out = r.step()
    # step 1 dispatched tick 1 (admission + first chunk); nothing was
    # in flight to collect, so no tokens can have been returned yet
    assert first_out == []
    seen = []
    pending_seen = 0
    for _ in range(16):
        if any(s._pending_tick is not None for s in r.replicas):
            pending_seen += 1
        seen.extend(tok for _rid, tok in r.step())
        if r.idle:
            break
    assert pending_seen > 0, "no tick was ever left in flight"
    assert r.results[rid] == seen[:len(r.results[rid])]
    # sync reference: same values
    ref = _fleet(cfg, params, False)
    ref.submit(np.arange(1, 10, dtype=np.int32), 3)
    assert ref.drain()[0] == r.results[rid]


def test_early_collect_on_preempt_and_drain(model):
    """External mutations collect the pending tick first: preempt_lru
    mid-flight loses no tokens (they stash and deliver at the next
    collect), and begin_drain starts from settled state."""
    cfg, params = model
    r = _fleet(cfg, params, True, offload=True, preempt_on_oom=True,
               swap_policy="recompute", protect_ticks=0)
    rids = [r.submit(p, 4) for p in _prompts(cfg, lens=(9, 12, 7))]
    for _ in range(4):
        r.step()
    target = r.replicas[r.placement[rids[0]]]
    assert target._pending_tick is not None or target._collected == []
    victim = target.preempt_lru(reason="test")
    # the early collect drained the in-flight tick before parking
    assert target._pending_tick is None
    out = r.drain()
    assert victim is None or victim in out
    # token identity with the synchronous reference, preemption included
    ref = _fleet(cfg, params, False)
    for p in _prompts(cfg, lens=(9, 12, 7)):
        ref.submit(p, 4)
    want = ref.drain()
    assert out == want
    # graceful drain under the async loop: settled, zero leaked blocks
    r2 = _fleet(cfg, params, True)
    for p in _prompts(cfg, lens=(9, 12, 7)):
        r2.submit(p, 4)
    r2.step(); r2.step()
    sched = r2.replicas[0]
    sched.begin_drain()
    assert sched._pending_tick is None
    produced, requeued = sched.drain_graceful()
    assert sched.engine.allocator.in_use == 0
    r2.replicas[1].begin_drain()
    r2.replicas[1].drain_graceful()


# ---------------------------------------------------------------------------
# worker pool semantics
# ---------------------------------------------------------------------------


def test_host_worker_pool_fifo_flush_and_errors():
    pool = HostWorkerPool(n_threads=2)
    done = []
    lock = threading.Lock()
    for i in range(32):
        pool.submit(lambda i=i: (time.sleep(0.001),
                                 lock.__enter__(), done.append(i),
                                 lock.__exit__(None, None, None)))
    pool.flush()
    assert sorted(done) == list(range(32))
    assert pool.pending == 0

    def boom():
        raise ValueError("worker task failed")

    pool.submit(boom)
    with pytest.raises(RuntimeError, match="host-worker task"):
        pool.flush()
    pool.flush()  # errors cleared at the barrier that reported them
    pool.close()
    with pytest.raises(RuntimeError, match="closed"):
        pool.submit(lambda: None)
    pool.close()  # idempotent


def test_worker_offloads_jsonl_and_gate_snapshot(model, tmp_path):
    """With the async loop armed, per-request JSONL emission rides the
    worker pool (marks carry thread names), the gate snapshot refresh
    runs off-thread, and gate_metrics overlays live counters so
    depth-bound routing state is never stale."""
    cfg, params = model
    path = str(tmp_path / "async.jsonl")
    with MetricsLogger(path) as mlog:
        reqtrace = ReqTracer(mlog)
        ledger = DispatchLedger(mlog, seq_source=reqtrace, emit_every=16)
        r = _fleet(cfg, params, True, metrics_log=mlog,
                   reqtrace=reqtrace, ledger=ledger)
        for s in r.replicas:
            s.gate_refresh_ticks = 1  # force a refresh on every collect
        for i, p in enumerate(_prompts(cfg)):
            r.submit(p, 4, session=i % 2)
        r.drain()
        r.log_summary()
        ledger.finalize()
    records = [json.loads(l) for l in open(path) if l.strip()]
    assert validate_stream(records) == []
    reqs = [rec for rec in records if rec.get("kind") == "request"]
    assert len(reqs) == len(_prompts(cfg))
    worker_marks = [
        rec for rec in records
        if rec.get("kind") == "overlap" and rec.get("ev") == "host"
        and rec.get("thread", "").startswith("pdt-host")
    ]
    assert any(m["name"] == "jsonl-emit" for m in worker_marks)
    assert any(m["name"] == "metrics-refresh" for m in worker_marks)
    # gate snapshot landed, and the overlay carries the live counters
    gm = r.replicas[0].gate_metrics()
    assert gm["queue_depth"] == 0 and "preemptible" in gm
    assert "ttft_p95_s" in gm  # the worker-refreshed percentile side
    # the union summary record (replica=-1) reached the stream
    unions = [rec for rec in records if rec.get("kind") == "overlap"
              and rec.get("ev") == "summary" and rec.get("replica") == -1]
    assert len(unions) == 1 and 0 < unions[0]["busy_frac"] <= 1.0


def test_worker_thread_marks_classify_not_idle():
    """Satellite: a gap overlapped only by a worker-thread host mark
    attributes to ``<name>@<thread>`` — overlapped host work is visible,
    not ``idle-no-work`` (and not other-replica serialization)."""
    recs = [
        {"kind": "overlap", "ev": "launch", "replica": 0,
         "program": "decode_tick", "t0": 0.0, "t1": 1.0, "seq0": 0,
         "seq1": 1, "done": 1.0},
        {"kind": "overlap", "ev": "launch", "replica": 0,
         "program": "decode_tick", "t0": 2.0, "t1": 3.0, "seq0": 4,
         "seq1": 5, "done": 3.0},
        {"kind": "overlap", "ev": "host", "replica": 0,
         "name": "jsonl-emit", "thread": "pdt-host-0",
         "t0": 1.1, "t1": 1.9, "seq0": 2, "seq1": 3},
    ]
    bubbles = classify_bubbles(recs)
    assert len(bubbles) == 1
    assert bubbles[0]["cause"] == "jsonl-emit@pdt-host-0"
    # apportioned shares: the worker mark's measured seconds plus the
    # uncovered remainder as idle
    shares = bubbles[0]["shares"]
    assert shares["jsonl-emit@pdt-host-0"] == pytest.approx(0.8)
    assert shares["idle-no-work"] == pytest.approx(0.2)


def test_other_replica_host_marks_count_as_serialization():
    """A gap overlapped by ANOTHER replica's main-thread host mark is
    the one loop doing that replica's tick — other-replica-tick."""
    recs = [
        {"kind": "overlap", "ev": "launch", "replica": 0,
         "program": "decode_tick", "t0": 0.0, "t1": 1.0, "seq0": 0,
         "seq1": 1, "done": 1.0},
        {"kind": "overlap", "ev": "launch", "replica": 0,
         "program": "decode_tick", "t0": 2.0, "t1": 3.0, "seq0": 6,
         "seq1": 7, "done": 3.0},
        {"kind": "overlap", "ev": "host", "replica": 1,
         "name": "tick-collect", "t0": 1.0, "t1": 2.0,
         "seq0": 2, "seq1": 3},
    ]
    bubbles = classify_bubbles(recs)
    assert bubbles[0]["cause"] == "other-replica-tick"
    assert bubbles[0]["shares"]["other-replica-tick"] == pytest.approx(1.0)


def test_shared_device_wait_split():
    """Round 16: the other replica's EXECUTION beyond its dispatch wall
    classifies as shared-device-wait, while a sync launch (wall contains
    execution) still reads other-replica-tick — the backend-honesty
    split."""
    recs = [
        {"kind": "overlap", "ev": "launch", "replica": 0,
         "program": "decode_tick", "t0": 0.0, "t1": 1.0, "seq0": 0,
         "seq1": 1, "done": 1.0},
        {"kind": "overlap", "ev": "launch", "replica": 0,
         "program": "decode_tick", "t0": 3.0, "t1": 4.0, "seq0": 6,
         "seq1": 7, "done": 4.0},
        # an ASYNC launch on replica 1: thin dispatch wall [1.0, 1.1],
        # execution pinned by a blocking fence to [1.1, 3.0]
        {"kind": "overlap", "ev": "launch", "replica": 1,
         "program": "decode_tick", "t0": 1.0, "t1": 1.1, "seq0": 2,
         "seq1": 3, "done": 3.0},
    ]
    bubbles = [b for b in classify_bubbles(recs) if b["replica"] == 0]
    shares = bubbles[0]["shares"]
    assert shares["other-replica-tick"] == pytest.approx(0.1, abs=1e-6)
    assert shares["shared-device-wait"] == pytest.approx(1.9, abs=1e-6)


def test_fleet_busy_summary_union():
    """Overlapping busy slices across replicas merge: the union never
    double-counts the shared window."""
    recs = [
        {"kind": "overlap", "ev": "launch", "replica": 0,
         "program": "p", "t0": 0.0, "t1": 2.0, "seq0": 0, "seq1": 1,
         "done": 2.0},
        {"kind": "overlap", "ev": "launch", "replica": 1,
         "program": "p", "t0": 1.0, "t1": 3.0, "seq0": 2, "seq1": 3,
         "done": 3.0},
    ]
    fb = fleet_busy_summary(recs)
    assert fb["union_busy_s"] == pytest.approx(3.0)
    assert fb["window_s"] == pytest.approx(3.0)
    assert fb["union_busy_frac"] == pytest.approx(1.0)
    # per-replica fractions sum past the union (the double-count the
    # union exists to avoid)
    assert sum(fb["replicas"].values()) > fb["union_busy_frac"]


# ---------------------------------------------------------------------------
# guards: no hot sync, no recompiles, collect-site completion
# ---------------------------------------------------------------------------


def test_async_loop_no_hot_sync_and_no_recompile(model):
    """Acceptance: the ledger's no-hot-sync guard and ``no_recompile``
    stay green with the async loop armed — dispatch-then-collect adds
    zero program variants and never fences a launch newer than the
    lag."""
    cfg, params = model
    ledger = DispatchLedger(lag=2)
    r = _fleet(cfg, params, True, ledger=ledger)
    for i, p in enumerate(_prompts(cfg)):
        r.submit(p, 4, session=i % 2)
    for _ in range(6):
        r.step()
    for s in r.replicas:
        s.engine._decode_fn = no_recompile(s.engine._decode(),
                                           warmup_steps=1)
    for p in _prompts(cfg, lens=(10, 11), seed=1):
        r.submit(p, 4)
    r.drain()
    for s in r.replicas:
        stats = s.engine._decode_fn.stats
        assert stats.recompiles_after_warmup == 0
    assert ledger.hot_fences == 0
    assert ledger.dead_fences == 0
    # async decode launches were pinned at their collect site
    launches = [rec for rec in ledger.records
                if rec.get("ev") == "launch"
                and rec.get("program") == "decode_tick"]
    assert any(rec.get("collected") or rec.get("fenced")
               for rec in launches)


def test_registry_coverage_with_async_loop(model):
    cfg, params = model
    r = _fleet(cfg, params, True)
    for p in _prompts(cfg):
        r.submit(p, 3)
    r.drain()
    r.assert_registry_covers()


# ---------------------------------------------------------------------------
# kill matrix: SIGKILL mid-swap under the async loop
# ---------------------------------------------------------------------------


def _run_serve_child(save_dir, env_extra=None, timeout=300):
    env = dict(os.environ)
    env.pop(faults.ENV_PLAN, None)
    env.update(env_extra or {})
    return subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(__file__), "serve_child.py"),
         "--save-dir", str(save_dir), "--fleet-async"],
        env=env, capture_output=True, text=True, timeout=timeout,
    )


@pytest.mark.slow
@pytest.mark.crash
def test_kill_matrix_async_loop_sigkill_mid_swap(tmp_path, model):
    """The async-loop kill-matrix cell: run 1 (2-replica async fleet,
    forced swap preemptions, ticks in flight, workers holding queued
    telemetry) dies by SIGKILL inside the swap-out window; run 2
    relaunches clean and serves token streams identical to the
    unpreempted greedy reference."""
    from tests.serve_child import workload
    from tests.test_pressure import greedy_streams

    plan = FaultPlan([FaultSpec(site="kv.swap_out_d2h", kind="kill",
                                at=0)])
    r1 = _run_serve_child(tmp_path, {faults.ENV_PLAN: plan.to_json()})
    assert r1.returncode == -signal.SIGKILL, (
        f"child should die by SIGKILL; rc={r1.returncode}\n"
        f"stdout:{r1.stdout}\nstderr:{r1.stderr}"
    )
    assert not os.path.exists(os.path.join(str(tmp_path), "result.json"))
    r2 = _run_serve_child(tmp_path)
    assert r2.returncode == 0, (
        f"relaunch failed\nstdout:{r2.stdout}\nstderr:{r2.stderr}"
    )
    with open(os.path.join(str(tmp_path), "result.json")) as f:
        result = json.load(f)
    assert result["preempts"] >= 1 and result["swap_aborts"] == 0
    cfg, params = model
    prompts = workload(cfg)
    want = greedy_streams(cfg, params, prompts, 6)
    for i in range(len(prompts)):
        assert result["streams"][str(i)] == want[i], f"stream {i}"


# ---------------------------------------------------------------------------
# lint: every new/worker module rules_threads-clean
# ---------------------------------------------------------------------------


def test_rules_threads_clean_on_async_modules():
    """Satellite gate: every module the async refactor gave threads or
    thread-shared state to passes the concurrency lints with zero
    findings — locks (or documented lock-free protocols) on every
    shared structure."""
    ctx = LintContext(modules=[], mesh_axes=set(), axis_constants={})
    for rel in (
        "pytorch_distributed_tpu/serving/host_worker.py",
        "pytorch_distributed_tpu/serving/scheduler.py",
        "pytorch_distributed_tpu/fleet/router.py",
        "pytorch_distributed_tpu/telemetry/overlap.py",
        "pytorch_distributed_tpu/telemetry/anomaly.py",
        "pytorch_distributed_tpu/utils/profiling.py",
    ):
        mod = parse_file(os.path.join(REPO, rel), REPO)
        findings = check_threads(mod, ctx)
        assert findings == [], [f.render() for f in findings]
    inv = thread_inventory(parse_file(
        os.path.join(REPO, "pytorch_distributed_tpu/serving/host_worker.py"),
        REPO,
    ))
    assert inv["threads"], "the worker pool's threads must be inventoried"
    assert inv["threads"][0]["kind"] == "self-method"
