"""Pallas paged-attention kernel + int8 quantized KV pool (round 12
tentpole): fused-gather vs dense-gather parity at the op level and as
token-identical greedy streams (single device AND TP=2, GQA included),
chunked-vs-whole prefill equivalence through the kernel, the int8 pool's
documented accuracy bound (logit max-abs-err + token-match rate), the
~2x capacity-at-fixed-bytes claim, and registry coverage over every new
program shape (pallas vs dense × int8 vs raw).

Round 20 (kernel tier 2) grows the file along the same axes: fp8 pools
(e4m3/e5m2 with int8 power-of-two exponent scales — layout, logit error
budget, token-match rate, the 2D/(D+1) >= 1.9x capacity claim), the
fused quantize-on-scatter's bit-equivalence to the jnp spelling per
pool dtype, the flash-decoding split's parity with the single-worker
sweep plus its auto policy, an fp8+split serve cycle, and fingerprint
distinctness over the new variants."""

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_tpu.models.generate import ContinuousBatcher, generate
from pytorch_distributed_tpu.models.transformer import (
    TransformerLM,
    tiny_config,
)
from pytorch_distributed_tpu.ops.attention import paged_attention
from pytorch_distributed_tpu.ops.paged_flash import (
    auto_split_s,
    paged_flash_attention,
    paged_quantize_scatter,
)
from pytorch_distributed_tpu.serving import PagedEngine, Scheduler
from pytorch_distributed_tpu.serving.engine import ChunkJob
from pytorch_distributed_tpu.serving.kv_pool import (
    init_paged_cache,
    kv_pool_dtype,
    pool_block_bytes,
    pool_scale_dtype,
    quantize_kv,
)


def setup(max_seq_len=96, **over):
    cfg = tiny_config(attention="dense", max_seq_len=max_seq_len, **over)
    params = TransformerLM(cfg).init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return cfg, params


def greedy_reference(cfg, params, prompt, max_new):
    full = generate(
        cfg, params, jnp.asarray(prompt)[None, :], jax.random.key(1),
        max_new_tokens=max_new, temperature=0.0,
    )
    return list(np.asarray(full)[0, len(prompt):])


def random_pool(rng, b, h_kv, d, bl, w, quantize=False):
    """Non-contiguous block chains in a shared pool + absolute query
    positions — the op-level fixture (mirrors test_paged_serving's)."""
    n_blocks = 1 + b * w
    pool_k = np.zeros((n_blocks, bl, h_kv, d), np.float32)
    pool_v = np.zeros((n_blocks, bl, h_kv, d), np.float32)
    tables = np.zeros((b, w), np.int32)
    order = rng.permutation(np.arange(1, n_blocks))
    for bi in range(b):
        for wi in range(w):
            blk = int(order[bi * w + wi])
            tables[bi, wi] = blk
            pool_k[blk] = rng.normal(size=(bl, h_kv, d))
            pool_v[blk] = rng.normal(size=(bl, h_kv, d))
    args = [jnp.asarray(pool_k), jnp.asarray(pool_v)]
    scales = {}
    if quantize:
        kq, ks = quantize_kv(args[0])
        vq, vs = quantize_kv(args[1])
        args = [kq, vq]
        scales = dict(k_scale=ks, v_scale=vs)
    return args[0], args[1], jnp.asarray(tables), scales


# ---------------------------------------------------------------------------
# op-level parity: the fused kernel vs the dense gather (fast tier)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("h_kv,c", [(4, 1), (4, 5), (2, 5), (2, 1)])
def test_paged_flash_matches_dense_gather(h_kv, c):
    """Same pools, same tables, same positions: the pallas spelling must
    reproduce the dense spelling — decode (C=1) and chunk (C=5) rows,
    MHA and GQA groupings, ragged per-request frontiers."""
    b, h, d, bl, w = 2, 4, 8, 4, 3
    rng = np.random.default_rng(0)
    kp, vp, tables, _ = random_pool(rng, b, h_kv, d, bl, w)
    q = jnp.asarray(rng.normal(size=(b, c, h, d)).astype(np.float32))
    L = w * bl
    q_positions = jnp.asarray(np.stack([
        np.arange(L - c, L), np.arange(3, 3 + c)
    ])[:b].astype(np.int32))
    dense = paged_attention(q, kp, vp, tables, q_positions,
                            gather_impl="dense")
    pallas = paged_attention(q, kp, vp, tables, q_positions,
                             gather_impl="pallas")
    np.testing.assert_allclose(
        np.asarray(pallas), np.asarray(dense), rtol=1e-5, atol=1e-5
    )


@pytest.mark.parametrize("c", [1, 5])
def test_paged_flash_int8_matches_dense_int8(c):
    """Both spellings dequantize the SAME stored rows, so on a quantized
    pool they must agree to fp tolerance (the quantization error itself
    is shared, not a divergence between them)."""
    b, h, h_kv, d, bl, w = 2, 4, 2, 8, 4, 3
    rng = np.random.default_rng(1)
    kq, vq, tables, scales = random_pool(rng, b, h_kv, d, bl, w,
                                         quantize=True)
    q = jnp.asarray(rng.normal(size=(b, c, h, d)).astype(np.float32))
    q_positions = jnp.asarray(
        np.stack([np.arange(c), np.arange(7, 7 + c)])[:b].astype(np.int32)
    )
    dense = paged_attention(q, kq, vq, tables, q_positions,
                            gather_impl="dense", **scales)
    pallas = paged_attention(q, kq, vq, tables, q_positions,
                             gather_impl="pallas", **scales)
    np.testing.assert_allclose(
        np.asarray(pallas), np.asarray(dense), rtol=1e-5, atol=1e-5
    )


def test_quantize_kv_roundtrip_bound():
    """Symmetric per-row int8: dequantized values within one step
    (scale = amax/127) of the original, exact at the row max."""
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(3, 7, 2, 16)).astype(np.float32))
    q, s = quantize_kv(x)
    assert q.dtype == jnp.int8 and s.shape == x.shape[:-1]
    deq = np.asarray(q, np.float32) * np.asarray(s)[..., None]
    step = np.asarray(s)[..., None]  # one quantization step per row
    assert np.abs(deq - np.asarray(x)).max() <= (step / 2 + 1e-7).max()
    assert np.abs(deq - np.asarray(x)).max() > 0  # really quantized


def test_paged_attention_scale_arg_validation():
    z = jnp.zeros((1, 1, 2, 4))
    pool = jnp.zeros((2, 4, 2, 4))
    pool8 = jnp.zeros((2, 4, 2, 4), jnp.int8)
    sc = jnp.ones((2, 4, 2))
    t = jnp.zeros((1, 1), jnp.int32)
    p = jnp.zeros((1, 1), jnp.int32)
    for impl in ("dense", "pallas"):
        with pytest.raises(ValueError, match="k_scale"):
            paged_attention(z, pool8, pool8, t, p, gather_impl=impl)
        with pytest.raises(ValueError, match="k_scale"):
            paged_attention(z, pool, pool, t, p, gather_impl=impl,
                            k_scale=sc, v_scale=sc)


# ---------------------------------------------------------------------------
# int8 pool accuracy bound (fast tier — THE documented numbers)
# ---------------------------------------------------------------------------


def _final_logits(cfg, params, prompt, kv_dtype):
    eng = PagedEngine(cfg, params, n_slots=1, block_len=8,
                      prefill_chunk=8, kv_dtype=kv_dtype)
    assert eng.admit(0, len(prompt), 4)
    chunk = np.zeros((8,), np.int32)
    chunk[:len(prompt)] = prompt
    eng.run_chunks([ChunkJob(0, chunk, 0, True, len(prompt) - 1)])
    return np.asarray(eng.logits[0])


@functools.lru_cache(maxsize=None)
def _pool_final_logits(kv_dtype):
    """Final-prefill logits on the fixed accuracy prompt, one engine
    build per pool dtype shared by the int8 AND fp8 bound tests (the
    raw-pool reference engine is the expensive common factor)."""
    cfg, params = setup()
    rng = np.random.default_rng(5)
    prompt = rng.integers(1, cfg.vocab_size, (8,)).astype(np.int32)
    return _final_logits(cfg, params, prompt, kv_dtype)


@functools.lru_cache(maxsize=None)
def _pool_greedy_streams(kv_dtype):
    """Greedy streams over the fixed 4-prompt set for one pool dtype —
    the raw-pool scheduler run is shared by both token-match tests."""
    cfg, params = setup()
    rng = np.random.default_rng(6)
    prompts = [rng.integers(1, cfg.vocab_size, (l,)).astype(np.int32)
               for l in (5, 9, 13, 7)]
    s = Scheduler(cfg, params, n_slots=2, block_len=8, prefill_chunk=8,
                  kv_dtype=kv_dtype)
    rids = [s.submit(p, 6) for p in prompts]
    out = s.drain()
    return tuple(tuple(out[r]) for r in rids)


def _match_rate(kv_dtype):
    raw = _pool_greedy_streams(None)
    quant = _pool_greedy_streams(kv_dtype)
    pairs = [(a, b) for r, q in zip(raw, quant) for a, b in zip(r, q)]
    assert len(pairs) == 4 * 6
    return sum(int(a == b) for a, b in pairs) / len(pairs)


@pytest.mark.slow
def test_int8_pool_logit_error_bound():
    """The documented quantization error budget (ANALYSIS.md "Paged
    attention kernel & quantized KV"): per-row symmetric int8 KV holds
    final-prefill logits within max-abs-err 0.05 of the raw pool on the
    test model (measured ~0.008 at logit scale ~3.3 — the bound leaves
    ~6x slack for parametric drift while staying falsifiable)."""
    err = np.abs(_pool_final_logits(None)
                 - _pool_final_logits("int8")).max()
    assert 0 < err <= 0.05, f"int8 logit max-abs-err {err}"


@pytest.mark.slow
def test_int8_pool_token_match_rate():
    """Short greedy decodes on the int8 pool must match the raw pool's
    streams at >= 90% of tokens (documented bound; exact match is NOT
    guaranteed — argmax can flip where the raw margin is inside the
    quantization error). One gather spelling suffices: pallas-vs-dense
    parity on the SAME pool dtype is proven separately, so the int8-vs-
    raw delta is spelling-independent."""
    rate = _match_rate("int8")
    assert rate >= 0.9, f"int8 token match rate {rate:.2f}"


def test_int8_pool_capacity_ratio_at_fixed_bytes():
    """The capacity claim: at a fixed pool byte budget, the int8 pool
    (1 byte/elem + 4-byte fp32 row scale per head) fits ~2x the blocks
    of a bf16 pool — exactly 2D/(D+4), i.e. 1.88x at D=64. Asserted
    from pure eval_shape arithmetic (pool_block_bytes), no allocation."""
    cfg, params = setup(dtype=jnp.bfloat16, num_heads=4, embed_dim=256)
    bf16 = pool_block_bytes(cfg, params, block_len=16)
    int8 = pool_block_bytes(cfg, params, block_len=16, kv_dtype="int8")
    d = cfg.embed_dim // cfg.num_heads  # 64
    assert bf16 / int8 == pytest.approx(2 * d / (d + 4), rel=1e-6)
    budget = 1 << 20
    assert (budget // int8) / (budget // bf16) >= 1.8


def test_fp8_pool_logit_error_bound():
    """The round 20 fp8 error budget (ANALYSIS.md "Kernel speed tier
    2"): e4m3 KV (3 mantissa bits, power-of-two row exponents so the
    scale multiply is exact) holds final-prefill logits within
    max-abs-err 0.1 of the raw pool. e5m2 trades a mantissa bit for
    range it doesn't need under per-row exponents — its error is
    strictly worse than e4m3's on the same prompt, which is why e4m3
    is the default."""
    raw = _pool_final_logits(None)
    e4 = np.abs(raw - _pool_final_logits("fp8")).max()
    e5 = np.abs(raw - _pool_final_logits("fp8_e5m2")).max()
    assert 0 < e4 <= 0.1, f"fp8(e4m3) logit max-abs-err {e4}"
    assert e4 < e5, f"e4m3 ({e4}) should beat e5m2 ({e5})"


@pytest.mark.slow
def test_fp8_pool_token_match_rate():
    """Short greedy decodes on the e4m3 pool must match the raw pool's
    streams at >= 90% of tokens — same documented bound as int8 (argmax
    can flip where the raw margin is inside the quantization error),
    same spelling-independence argument."""
    rate = _match_rate("fp8")
    assert rate >= 0.9, f"fp8 token match rate {rate:.2f}"


def test_fp8_pool_capacity_ratio_at_fixed_bytes():
    """The fp8 capacity claim: 1 byte/elem + a 1-byte int8 exponent per
    row per head gives exactly 2D/(D+1) vs bf16 — 1.969x at D=64,
    clearing the >= 1.9 bar the int8 layout's fp32 scales miss
    (2D/(D+4) = 1.88x). fp8 also strictly beats int8 at the same
    budget. Pure eval_shape arithmetic, no allocation."""
    cfg, params = setup(dtype=jnp.bfloat16, num_heads=4, embed_dim=256)
    bf16 = pool_block_bytes(cfg, params, block_len=16)
    int8 = pool_block_bytes(cfg, params, block_len=16, kv_dtype="int8")
    fp8 = pool_block_bytes(cfg, params, block_len=16, kv_dtype="fp8")
    d = cfg.embed_dim // cfg.num_heads  # 64
    assert bf16 / fp8 == pytest.approx(2 * d / (d + 1), rel=1e-6)
    assert bf16 / fp8 >= 1.9
    assert fp8 < int8
    budget = 1 << 20
    assert budget // fp8 > budget // int8 > budget // bf16
    assert pool_block_bytes(cfg, params, block_len=16,
                            kv_dtype="fp8_e5m2") == fp8


def test_init_paged_cache_int8_layout():
    cfg, params = setup(num_heads=4, num_kv_heads=2)
    cache = init_paged_cache(cfg, params, n_blocks=4, block_len=8,
                             kv_dtype="int8")
    layer = cache["block0"]["attn"]
    assert set(layer) == {"key", "value", "key_scale", "value_scale"}
    assert layer["key"].dtype == jnp.int8
    assert layer["key_scale"].dtype == jnp.float32
    assert layer["key"].shape == (4, 8, 2, 8)  # head_dim 32/4
    assert layer["key_scale"].shape == (4, 8, 2)
    with pytest.raises(ValueError, match="kv_dtype"):
        init_paged_cache(cfg, params, 4, 8, kv_dtype="fp4")


def test_init_paged_cache_fp8_layout():
    """fp8 pool layout: e4m3 storage with INT8 power-of-two exponent
    scale siblings (1 byte per row per head — the source of the
    2D/(D+1) capacity edge over int8's fp32 scales), e5m2 selectable."""
    cfg, params = setup(num_heads=4, num_kv_heads=2)
    cache = init_paged_cache(cfg, params, n_blocks=4, block_len=8,
                             kv_dtype="fp8")
    layer = cache["block0"]["attn"]
    assert set(layer) == {"key", "value", "key_scale", "value_scale"}
    assert layer["key"].dtype == jnp.float8_e4m3fn
    assert layer["key_scale"].dtype == jnp.int8
    assert layer["key"].shape == (4, 8, 2, 8)
    assert layer["key_scale"].shape == (4, 8, 2)
    e5 = init_paged_cache(cfg, params, n_blocks=4, block_len=8,
                          kv_dtype="fp8_e5m2")
    assert e5["block0"]["attn"]["value"].dtype == jnp.float8_e5m2
    assert e5["block0"]["attn"]["value_scale"].dtype == jnp.int8


# ---------------------------------------------------------------------------
# quantize-on-scatter: the fused write path vs the jnp spelling
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("kv_dtype", ["int8", "fp8", "fp8_e5m2"])
def test_quantize_scatter_bit_equivalence(kv_dtype):
    """The write-side contract: the Pallas quantize-on-scatter and the
    jnp spelling (quantize_kv + four .at[rows].set) share
    kv_pool.quantize_rows, so pools AND scale siblings must come out
    BIT-identical for every pool dtype — not merely close. Destination
    rows are unique (duplicate rows would make the jnp .at[].set
    order-undefined, which is a fixture artifact, not a kernel
    property)."""
    b, l, h_kv, d, bl, nb = 2, 6, 2, 8, 4, 7
    rng = np.random.default_rng(7)
    pool_dt = kv_pool_dtype(kv_dtype)
    scale_dt = pool_scale_dtype(pool_dt)
    k = jnp.asarray(rng.normal(size=(b, l, h_kv, d)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(b, l, h_kv, d)).astype(np.float32))
    flat = rng.choice((nb - 1) * bl, size=b * l, replace=False)
    blk = jnp.asarray((flat // bl + 1).reshape(b, l).astype(np.int32))
    off = jnp.asarray((flat % bl).reshape(b, l).astype(np.int32))

    def pools():
        return (jnp.zeros((nb, bl, h_kv, d), pool_dt),
                jnp.zeros((nb, bl, h_kv, d), pool_dt),
                jnp.zeros((nb, bl, h_kv), scale_dt),
                jnp.zeros((nb, bl, h_kv), scale_dt))

    kp, vp, ks, vs = paged_quantize_scatter(k, v, blk, off, *pools())
    rkp, rvp, rks, rvs = pools()
    qk, sk = quantize_kv(k, pool_dt)
    qv, sv = quantize_kv(v, pool_dt)
    rows = (blk.reshape(-1), off.reshape(-1))
    rkp = rkp.at[rows].set(qk.reshape(-1, h_kv, d))
    rvp = rvp.at[rows].set(qv.reshape(-1, h_kv, d))
    rks = rks.at[rows].set(sk.reshape(-1, h_kv))
    rvs = rvs.at[rows].set(sv.reshape(-1, h_kv))
    for got, ref in ((kp, rkp), (vp, rvp), (ks, rks), (vs, rvs)):
        assert got.dtype == ref.dtype
        np.testing.assert_array_equal(
            np.asarray(got).view(np.uint8), np.asarray(ref).view(np.uint8)
        )


def test_quantize_scatter_rejects_raw_pools():
    z = jnp.zeros((1, 1, 2, 4))
    pool = jnp.zeros((2, 4, 2, 4), jnp.float32)
    sc = jnp.zeros((2, 4, 2), jnp.float32)
    i = jnp.zeros((1, 1), jnp.int32)
    with pytest.raises(ValueError, match="quantized"):
        paged_quantize_scatter(z, z, i, i, pool, pool, sc, sc)


# ---------------------------------------------------------------------------
# flash-decoding split: S workers must reproduce the single sweep
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("split_s,c", [
    (2, 1), (8, 5), (3, 1), (2, 5),
    pytest.param(8, 1, marks=pytest.mark.slow),
    pytest.param(3, 5, marks=pytest.mark.slow),
])
def test_split_s_matches_single_worker(split_s, c):
    """The combine algebra under test: S workers' un-normalized
    (m, l, acc) partials merged by fp32 log-sum-exp must reproduce the
    single-worker sweep to <= 1e-3 (documented bound; measured ~1e-7 —
    the combine is a different fp32 reduction order, not a different
    function). Decode (C=1) and chunk (C=5) rows, ragged frontiers, a
    12-block chain so 8 workers leave some workers empty."""
    b, h, h_kv, d, bl, w = 2, 4, 2, 16, 4, 12
    rng = np.random.default_rng(8)
    kp, vp, tables, _ = random_pool(rng, b, h_kv, d, bl, w)
    q = jnp.asarray(rng.normal(size=(b, c, h, d)).astype(np.float32))
    ends = [37, 22]
    q_positions = jnp.asarray(np.stack([
        np.arange(e - c + 1, e + 1) for e in ends
    ]).astype(np.int32))
    single = paged_flash_attention(q, kp, vp, tables, q_positions,
                                   split_s=1)
    split = paged_flash_attention(q, kp, vp, tables, q_positions,
                                  split_s=split_s)
    err = np.abs(np.asarray(split) - np.asarray(single)).max()
    assert err <= 1e-3, f"split_s={split_s} parity err {err}"


@pytest.mark.slow
def test_split_s_quantized_pool():
    """The split path also dequantizes: int8 and fp8 pools through S=4
    workers match their own single-worker sweep."""
    b, h, h_kv, d, bl, w = 2, 4, 2, 16, 4, 12
    for seed, kv_dtype in ((9, "int8"), (10, "fp8")):
        rng = np.random.default_rng(seed)
        kp, vp, tables, _ = random_pool(rng, b, h_kv, d, bl, w)
        qk, ks = quantize_kv(kp, kv_pool_dtype(kv_dtype))
        qv, vs = quantize_kv(vp, kv_pool_dtype(kv_dtype))
        q = jnp.asarray(rng.normal(size=(b, 1, h, d)).astype(np.float32))
        pos = jnp.asarray([[41], [19]], jnp.int32)
        one = paged_flash_attention(q, qk, qv, tables, pos,
                                    k_scale=ks, v_scale=vs, split_s=1)
        four = paged_flash_attention(q, qk, qv, tables, pos,
                                     k_scale=ks, v_scale=vs, split_s=4)
        err = np.abs(np.asarray(four) - np.asarray(one)).max()
        assert err <= 1e-3, f"{kv_dtype} split parity err {err}"


def test_auto_split_s_policy():
    """The threshold policy is static-shape arithmetic: split only when
    W/B crosses the threshold (few long chains), then min(MAX_SPLIT, W)
    so every worker owns >= 1 block; split_s=None in the op resolves
    through it, and split_s < 1 is rejected everywhere it can enter."""
    assert auto_split_s(64, 2) == 8
    assert auto_split_s(8, 8) == 1
    assert auto_split_s(16, 1) == 8
    assert auto_split_s(7, 1) == 1  # 7 // 1 < 8: below threshold
    assert auto_split_s(160, 1, max_split=4) == 4
    # op-level: None == the policy's pick, bit-for-bit (same program)
    b, h, h_kv, d, bl, w = 2, 4, 2, 8, 4, 3
    rng = np.random.default_rng(11)
    kp, vp, tables, _ = random_pool(rng, b, h_kv, d, bl, w)
    q = jnp.asarray(rng.normal(size=(b, 1, h, d)).astype(np.float32))
    pos = jnp.asarray([[9], [5]], jnp.int32)
    auto = paged_flash_attention(q, kp, vp, tables, pos)  # W/B=1 → 1
    one = paged_flash_attention(q, kp, vp, tables, pos, split_s=1)
    np.testing.assert_array_equal(np.asarray(auto), np.asarray(one))
    with pytest.raises(ValueError, match="split_s"):
        paged_flash_attention(q, kp, vp, tables, pos, split_s=0)
    with pytest.raises(ValueError, match="split_s"):
        dataclasses.replace(setup(max_seq_len=64)[0], split_s=0)


# ---------------------------------------------------------------------------
# registry coverage: every new program shape predicted (fast tier)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("gather_impl,kv_dtype", [
    ("pallas", None), ("dense", "int8"),
    pytest.param("pallas", "int8", marks=pytest.mark.slow),
    pytest.param("pallas", "fp8", marks=pytest.mark.slow),
])
def test_registry_covers_kernel_and_quant_variants(gather_impl, kv_dtype):
    """The coverage guard keeps its teeth over the new program shapes:
    a pallas/int8 engine's compiled programs are all predicted by its
    serving registry, and each (gather_impl, kv_dtype) combination keys
    a DISTINCT run fingerprint (an artifact from one variant can never
    load as another's program)."""
    from pytorch_distributed_tpu.compilecache import serving_registry

    cfg, params = setup()
    eng = PagedEngine(cfg, params, n_slots=2, block_len=8,
                      prefill_chunk=8, gather_impl=gather_impl,
                      kv_dtype=kv_dtype)
    reg = serving_registry(eng)
    eng.warm_decode()
    eng.warm_chunk(1, 1)
    reg.assert_covers(eng.compiled_program_names())
    base = serving_registry(PagedEngine(
        cfg, params, n_slots=2, block_len=8, prefill_chunk=8,
    ))
    assert reg.fingerprint != base.fingerprint


def test_registry_distinct_fingerprints_tier2_variants():
    """Every tier-2 knob keys a distinct fingerprint: e4m3 vs e5m2 vs
    int8 pools and split vs unsplit programs can never load each
    other's compiled artifacts."""
    from pytorch_distributed_tpu.compilecache import serving_registry

    cfg, params = setup()
    variants = [
        dict(kv_dtype="int8"),
        dict(kv_dtype="fp8"),
        dict(kv_dtype="fp8_e5m2"),
        dict(kv_dtype="fp8", split_s=2),
        dict(kv_dtype="fp8", split_s=4),
    ]
    fps = [
        serving_registry(PagedEngine(
            cfg, params, n_slots=2, block_len=8, prefill_chunk=8,
            gather_impl="pallas", **kw,
        )).fingerprint
        for kw in variants
    ]
    assert len(set(fps)) == len(fps), fps


# ---------------------------------------------------------------------------
# serve-cycle smoke (slow tier; ci_check.sh --kernel-smoke runs it by id)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_kernel_smoke():
    """One full pallas-path serve cycle on the int8 pool: submit →
    chunked prefill → decode → drain, token-identical to the replicated
    ``generate`` reference, blocks returned to the pool."""
    cfg, params = setup(max_seq_len=64)
    s = Scheduler(cfg, params, n_slots=2, block_len=8, prefill_chunk=8,
                  gather_impl="pallas", kv_dtype="int8")
    assert s.engine.gather_impl == "pallas"
    prompt = np.arange(1, 10, dtype=np.int32)
    rid = s.submit(prompt, 4)
    out = s.drain()[rid]
    assert out == greedy_reference(cfg, params, prompt, 4)
    assert s.engine.allocator.in_use == 0


@pytest.mark.slow
def test_fp8_serve_cycle_split_s():
    """One full serve cycle on the fp8 pool with the split decode
    (pallas gather, split_s=2): token-identical to the DENSE-gather
    scheduler on the same pool dtype (the shared ``_pool_greedy_streams``
    fixture — default gather is dense) — equal pools isolate the kernel
    spellings (quantization error is shared, bit-equal by the scatter
    test), leaving only ~1e-7 reduction-order noise. Blocks return to
    the pool."""
    cfg, params = setup()
    rng = np.random.default_rng(6)
    prompts = [rng.integers(1, cfg.vocab_size, (l,)).astype(np.int32)
               for l in (5, 9, 13, 7)]
    s = Scheduler(cfg, params, n_slots=2, block_len=8, prefill_chunk=8,
                  gather_impl="pallas", kv_dtype="fp8", split_s=2)
    assert s.engine.config.split_s == 2
    rids = [s.submit(p, 6) for p in prompts]
    out = s.drain()
    assert tuple(tuple(out[r]) for r in rids) == _pool_greedy_streams("fp8")
    assert s.engine.allocator.in_use == 0


@pytest.mark.slow
def test_chunked_vs_whole_prefill_pallas():
    """Chunk boundaries cannot change the kernel's math: a 29-token
    prompt prefilled in 8-token chunks streams the same greedy tokens
    as whole-prompt prefill (the ``generate`` reference IS the
    whole-prefill path), through the pallas gather."""
    cfg, params = setup()
    rng = np.random.default_rng(3)
    prompt = rng.integers(1, cfg.vocab_size, (29,)).astype(np.int32)
    ref = greedy_reference(cfg, params, prompt, 4)
    b = ContinuousBatcher(cfg, params, n_slots=1, prefill_bucket=8,
                          gather_impl="pallas")
    b.submit(prompt, 4)
    got = []
    while any(b.remaining > 0):
        got += [t for _s, t in b.step()]
    assert got == ref


# ---------------------------------------------------------------------------
# token-identical greedy streams (slow tier, like the r6 parity tests)
# ---------------------------------------------------------------------------


def _drive_batcher(b, prompts, budgets):
    got, slot_of, pending = {}, {}, list(range(len(prompts)))
    while pending or any(b.remaining > 0):
        while pending and b.free_slots():
            i = pending.pop(0)
            slot_of[i] = b.submit(prompts[i], budgets[i])
            got[i] = []
        for slot, token in b.step():
            req = next(i for i, s in slot_of.items()
                       if s == slot and len(got[i]) < budgets[i])
            got[req].append(token)
    return got


@pytest.mark.slow
@pytest.mark.parametrize("kv_heads", [None, 2])
def test_pallas_batcher_matches_dense_gather(kv_heads):
    """Staggered admissions, slot reuse, mixed budgets, MHA and GQA:
    the pallas gather must emit token-identical greedy streams to the
    dense gather over the same block pool."""
    cfg, params = setup(num_heads=4, num_kv_heads=kv_heads)
    rng = np.random.default_rng(1)
    prompts = [rng.integers(1, cfg.vocab_size, (l,)).astype(np.int32)
               for l in (7, 13, 4, 21)]
    budgets = [6, 10, 8, 5]
    dense = _drive_batcher(
        ContinuousBatcher(cfg, params, n_slots=2, prefill_bucket=8,
                          gather_impl="dense"),
        prompts, budgets,
    )
    pallas = _drive_batcher(
        ContinuousBatcher(cfg, params, n_slots=2, prefill_bucket=8,
                          gather_impl="pallas"),
        prompts, budgets,
    )
    assert dense == pallas


@pytest.mark.slow
@pytest.mark.parametrize("kv_heads,kv_dtype", [
    (None, None), (2, None), (2, "int8"),
])
def test_pallas_batcher_tp_matches_dense(kv_heads, kv_dtype):
    """TP=2 CPU mesh: the pallas kernel under shard_map (head-sharded
    pool AND head-sharded scale siblings for int8) matches the
    replicated DENSE-layout batcher token-for-token, GQA included."""
    from pytorch_distributed_tpu.parallel import make_mesh

    rep = tiny_config(attention="dense", max_seq_len=96, num_heads=4,
                      num_kv_heads=kv_heads)
    tpcfg = dataclasses.replace(rep, model_axis="model", tp_size=2)
    params = TransformerLM(rep).init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    mesh = make_mesh(jax.devices()[:2], data_parallel=1, seq_parallel=1,
                     model_parallel=2)
    rng = np.random.default_rng(2)
    prompts = [rng.integers(1, rep.vocab_size, (l,)).astype(np.int32)
               for l in (5, 11, 7)]
    budgets = [6, 6, 6]
    dense_rep = _drive_batcher(
        ContinuousBatcher(rep, params, n_slots=2, prefill_bucket=8,
                          cache_layout="dense"),
        prompts, budgets,
    )
    tp = ContinuousBatcher(tpcfg, params, n_slots=2, prefill_bucket=8,
                           mesh=mesh, gather_impl="pallas",
                           kv_dtype=kv_dtype)
    assert _drive_batcher(tp, prompts, budgets) == dense_rep
    # the pool — and for int8 its scale siblings — really are sharded
    leaves = jax.tree.leaves(tp.cache)
    pools = [x for x in leaves if x.ndim == 4]
    assert next(iter(pools[0].addressable_shards)).data.shape[2] == \
        pools[0].shape[2] // 2
    if kv_dtype == "int8":
        scales = [x for x in leaves if x.ndim == 3]
        assert scales, "int8 pool should carry scale leaves"
        assert next(iter(scales[0].addressable_shards)).data.shape[2] == \
            scales[0].shape[2] // 2


@pytest.mark.slow
def test_pallas_batcher_tp_fp8_matches_single_device():
    """TP=2 CPU mesh on the fp8 pool: quantization is per-row-per-head
    (head-local math), so head-sharding cannot change it — the TP
    batcher must match a SINGLE-DEVICE fp8 pallas batcher token-for-
    token (not the raw reference: e4m3 error may legitimately flip an
    argmax vs raw, but never vs the same pool dtype). The e4m3 pool and
    its int8 exponent siblings are both head-sharded."""
    from pytorch_distributed_tpu.parallel import make_mesh

    rep = tiny_config(attention="dense", max_seq_len=96, num_heads=4,
                      num_kv_heads=2)
    tpcfg = dataclasses.replace(rep, model_axis="model", tp_size=2)
    params = TransformerLM(rep).init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    mesh = make_mesh(jax.devices()[:2], data_parallel=1, seq_parallel=1,
                     model_parallel=2)
    rng = np.random.default_rng(3)
    prompts = [rng.integers(1, rep.vocab_size, (l,)).astype(np.int32)
               for l in (5, 11, 7)]
    budgets = [6, 6, 6]
    single = _drive_batcher(
        ContinuousBatcher(rep, params, n_slots=2, prefill_bucket=8,
                          gather_impl="pallas", kv_dtype="fp8"),
        prompts, budgets,
    )
    tp = ContinuousBatcher(tpcfg, params, n_slots=2, prefill_bucket=8,
                           mesh=mesh, gather_impl="pallas",
                           kv_dtype="fp8")
    assert _drive_batcher(tp, prompts, budgets) == single
    leaves = jax.tree.leaves(tp.cache)
    pools = [x for x in leaves if x.ndim == 4]
    assert pools[0].dtype == jnp.float8_e4m3fn
    assert next(iter(pools[0].addressable_shards)).data.shape[2] == \
        pools[0].shape[2] // 2
    scales = [x for x in leaves if x.ndim == 3]
    assert scales and scales[0].dtype == jnp.int8
    assert next(iter(scales[0].addressable_shards)).data.shape[2] == \
        scales[0].shape[2] // 2
