"""Pallas paged-attention kernel + int8 quantized KV pool (round 12
tentpole): fused-gather vs dense-gather parity at the op level and as
token-identical greedy streams (single device AND TP=2, GQA included),
chunked-vs-whole prefill equivalence through the kernel, the int8 pool's
documented accuracy bound (logit max-abs-err + token-match rate), the
~2x capacity-at-fixed-bytes claim, and registry coverage over every new
program shape (pallas vs dense × int8 vs raw)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_tpu.models.generate import ContinuousBatcher, generate
from pytorch_distributed_tpu.models.transformer import (
    TransformerLM,
    tiny_config,
)
from pytorch_distributed_tpu.ops.attention import paged_attention
from pytorch_distributed_tpu.serving import PagedEngine, Scheduler
from pytorch_distributed_tpu.serving.engine import ChunkJob
from pytorch_distributed_tpu.serving.kv_pool import (
    init_paged_cache,
    pool_block_bytes,
    quantize_kv,
)


def setup(max_seq_len=96, **over):
    cfg = tiny_config(attention="dense", max_seq_len=max_seq_len, **over)
    params = TransformerLM(cfg).init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return cfg, params


def greedy_reference(cfg, params, prompt, max_new):
    full = generate(
        cfg, params, jnp.asarray(prompt)[None, :], jax.random.key(1),
        max_new_tokens=max_new, temperature=0.0,
    )
    return list(np.asarray(full)[0, len(prompt):])


def random_pool(rng, b, h_kv, d, bl, w, quantize=False):
    """Non-contiguous block chains in a shared pool + absolute query
    positions — the op-level fixture (mirrors test_paged_serving's)."""
    n_blocks = 1 + b * w
    pool_k = np.zeros((n_blocks, bl, h_kv, d), np.float32)
    pool_v = np.zeros((n_blocks, bl, h_kv, d), np.float32)
    tables = np.zeros((b, w), np.int32)
    order = rng.permutation(np.arange(1, n_blocks))
    for bi in range(b):
        for wi in range(w):
            blk = int(order[bi * w + wi])
            tables[bi, wi] = blk
            pool_k[blk] = rng.normal(size=(bl, h_kv, d))
            pool_v[blk] = rng.normal(size=(bl, h_kv, d))
    args = [jnp.asarray(pool_k), jnp.asarray(pool_v)]
    scales = {}
    if quantize:
        kq, ks = quantize_kv(args[0])
        vq, vs = quantize_kv(args[1])
        args = [kq, vq]
        scales = dict(k_scale=ks, v_scale=vs)
    return args[0], args[1], jnp.asarray(tables), scales


# ---------------------------------------------------------------------------
# op-level parity: the fused kernel vs the dense gather (fast tier)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("h_kv,c", [(4, 1), (4, 5), (2, 5), (2, 1)])
def test_paged_flash_matches_dense_gather(h_kv, c):
    """Same pools, same tables, same positions: the pallas spelling must
    reproduce the dense spelling — decode (C=1) and chunk (C=5) rows,
    MHA and GQA groupings, ragged per-request frontiers."""
    b, h, d, bl, w = 2, 4, 8, 4, 3
    rng = np.random.default_rng(0)
    kp, vp, tables, _ = random_pool(rng, b, h_kv, d, bl, w)
    q = jnp.asarray(rng.normal(size=(b, c, h, d)).astype(np.float32))
    L = w * bl
    q_positions = jnp.asarray(np.stack([
        np.arange(L - c, L), np.arange(3, 3 + c)
    ])[:b].astype(np.int32))
    dense = paged_attention(q, kp, vp, tables, q_positions,
                            gather_impl="dense")
    pallas = paged_attention(q, kp, vp, tables, q_positions,
                             gather_impl="pallas")
    np.testing.assert_allclose(
        np.asarray(pallas), np.asarray(dense), rtol=1e-5, atol=1e-5
    )


@pytest.mark.parametrize("c", [1, 5])
def test_paged_flash_int8_matches_dense_int8(c):
    """Both spellings dequantize the SAME stored rows, so on a quantized
    pool they must agree to fp tolerance (the quantization error itself
    is shared, not a divergence between them)."""
    b, h, h_kv, d, bl, w = 2, 4, 2, 8, 4, 3
    rng = np.random.default_rng(1)
    kq, vq, tables, scales = random_pool(rng, b, h_kv, d, bl, w,
                                         quantize=True)
    q = jnp.asarray(rng.normal(size=(b, c, h, d)).astype(np.float32))
    q_positions = jnp.asarray(
        np.stack([np.arange(c), np.arange(7, 7 + c)])[:b].astype(np.int32)
    )
    dense = paged_attention(q, kq, vq, tables, q_positions,
                            gather_impl="dense", **scales)
    pallas = paged_attention(q, kq, vq, tables, q_positions,
                             gather_impl="pallas", **scales)
    np.testing.assert_allclose(
        np.asarray(pallas), np.asarray(dense), rtol=1e-5, atol=1e-5
    )


def test_quantize_kv_roundtrip_bound():
    """Symmetric per-row int8: dequantized values within one step
    (scale = amax/127) of the original, exact at the row max."""
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(3, 7, 2, 16)).astype(np.float32))
    q, s = quantize_kv(x)
    assert q.dtype == jnp.int8 and s.shape == x.shape[:-1]
    deq = np.asarray(q, np.float32) * np.asarray(s)[..., None]
    step = np.asarray(s)[..., None]  # one quantization step per row
    assert np.abs(deq - np.asarray(x)).max() <= (step / 2 + 1e-7).max()
    assert np.abs(deq - np.asarray(x)).max() > 0  # really quantized


def test_paged_attention_scale_arg_validation():
    z = jnp.zeros((1, 1, 2, 4))
    pool = jnp.zeros((2, 4, 2, 4))
    pool8 = jnp.zeros((2, 4, 2, 4), jnp.int8)
    sc = jnp.ones((2, 4, 2))
    t = jnp.zeros((1, 1), jnp.int32)
    p = jnp.zeros((1, 1), jnp.int32)
    for impl in ("dense", "pallas"):
        with pytest.raises(ValueError, match="k_scale"):
            paged_attention(z, pool8, pool8, t, p, gather_impl=impl)
        with pytest.raises(ValueError, match="k_scale"):
            paged_attention(z, pool, pool, t, p, gather_impl=impl,
                            k_scale=sc, v_scale=sc)


# ---------------------------------------------------------------------------
# int8 pool accuracy bound (fast tier — THE documented numbers)
# ---------------------------------------------------------------------------


def _final_logits(cfg, params, prompt, kv_dtype):
    eng = PagedEngine(cfg, params, n_slots=1, block_len=8,
                      prefill_chunk=8, kv_dtype=kv_dtype)
    assert eng.admit(0, len(prompt), 4)
    chunk = np.zeros((8,), np.int32)
    chunk[:len(prompt)] = prompt
    eng.run_chunks([ChunkJob(0, chunk, 0, True, len(prompt) - 1)])
    return np.asarray(eng.logits[0])


def test_int8_pool_logit_error_bound():
    """The documented quantization error budget (ANALYSIS.md "Paged
    attention kernel & quantized KV"): per-row symmetric int8 KV holds
    final-prefill logits within max-abs-err 0.05 of the raw pool on the
    test model (measured ~0.008 at logit scale ~3.3 — the bound leaves
    ~6x slack for parametric drift while staying falsifiable)."""
    cfg, params = setup()
    rng = np.random.default_rng(5)
    prompt = rng.integers(1, cfg.vocab_size, (8,)).astype(np.int32)
    raw = _final_logits(cfg, params, prompt, None)
    quant = _final_logits(cfg, params, prompt, "int8")
    err = np.abs(raw - quant).max()
    assert 0 < err <= 0.05, f"int8 logit max-abs-err {err}"


def test_int8_pool_token_match_rate():
    """Short greedy decodes on the int8 pool must match the raw pool's
    streams at >= 90% of tokens (documented bound; exact match is NOT
    guaranteed — argmax can flip where the raw margin is inside the
    quantization error). One gather spelling suffices: pallas-vs-dense
    parity on the SAME pool dtype is proven separately, so the int8-vs-
    raw delta is spelling-independent."""
    cfg, params = setup()
    rng = np.random.default_rng(6)
    prompts = [rng.integers(1, cfg.vocab_size, (l,)).astype(np.int32)
               for l in (5, 9, 13, 7)]
    match = total = 0
    raw = Scheduler(cfg, params, n_slots=2, block_len=8, prefill_chunk=8)
    quant = Scheduler(cfg, params, n_slots=2, block_len=8,
                      prefill_chunk=8, kv_dtype="int8")
    rids_r = [raw.submit(p, 6) for p in prompts]
    rids_q = [quant.submit(p, 6) for p in prompts]
    out_r, out_q = raw.drain(), quant.drain()
    for rr, rq in zip(rids_r, rids_q):
        for a, b in zip(out_r[rr], out_q[rq]):
            total += 1
            match += int(a == b)
    assert total == 4 * 6
    rate = match / total
    assert rate >= 0.9, f"int8 token match rate {rate:.2f}"


def test_int8_pool_capacity_ratio_at_fixed_bytes():
    """The capacity claim: at a fixed pool byte budget, the int8 pool
    (1 byte/elem + 4-byte fp32 row scale per head) fits ~2x the blocks
    of a bf16 pool — exactly 2D/(D+4), i.e. 1.88x at D=64. Asserted
    from pure eval_shape arithmetic (pool_block_bytes), no allocation."""
    cfg, params = setup(dtype=jnp.bfloat16, num_heads=4, embed_dim=256)
    bf16 = pool_block_bytes(cfg, params, block_len=16)
    int8 = pool_block_bytes(cfg, params, block_len=16, kv_dtype="int8")
    d = cfg.embed_dim // cfg.num_heads  # 64
    assert bf16 / int8 == pytest.approx(2 * d / (d + 4), rel=1e-6)
    budget = 1 << 20
    assert (budget // int8) / (budget // bf16) >= 1.8


def test_init_paged_cache_int8_layout():
    cfg, params = setup(num_heads=4, num_kv_heads=2)
    cache = init_paged_cache(cfg, params, n_blocks=4, block_len=8,
                             kv_dtype="int8")
    layer = cache["block0"]["attn"]
    assert set(layer) == {"key", "value", "key_scale", "value_scale"}
    assert layer["key"].dtype == jnp.int8
    assert layer["key_scale"].dtype == jnp.float32
    assert layer["key"].shape == (4, 8, 2, 8)  # head_dim 32/4
    assert layer["key_scale"].shape == (4, 8, 2)
    with pytest.raises(ValueError, match="kv_dtype"):
        init_paged_cache(cfg, params, 4, 8, kv_dtype="fp8")


# ---------------------------------------------------------------------------
# registry coverage: every new program shape predicted (fast tier)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("gather_impl,kv_dtype", [
    ("pallas", None), ("dense", "int8"), ("pallas", "int8"),
])
def test_registry_covers_kernel_and_quant_variants(gather_impl, kv_dtype):
    """The coverage guard keeps its teeth over the new program shapes:
    a pallas/int8 engine's compiled programs are all predicted by its
    serving registry, and each (gather_impl, kv_dtype) combination keys
    a DISTINCT run fingerprint (an artifact from one variant can never
    load as another's program)."""
    from pytorch_distributed_tpu.compilecache import serving_registry

    cfg, params = setup()
    eng = PagedEngine(cfg, params, n_slots=2, block_len=8,
                      prefill_chunk=8, gather_impl=gather_impl,
                      kv_dtype=kv_dtype)
    reg = serving_registry(eng)
    eng.warm_decode()
    eng.warm_chunk(1, 1)
    reg.assert_covers(eng.compiled_program_names())
    base = serving_registry(PagedEngine(
        cfg, params, n_slots=2, block_len=8, prefill_chunk=8,
    ))
    assert reg.fingerprint != base.fingerprint


# ---------------------------------------------------------------------------
# serve-cycle smoke (fast tier — ci_check.sh --kernel-smoke runs this)
# ---------------------------------------------------------------------------


def test_kernel_smoke():
    """One full pallas-path serve cycle on the int8 pool: submit →
    chunked prefill → decode → drain, token-identical to the replicated
    ``generate`` reference, blocks returned to the pool."""
    cfg, params = setup(max_seq_len=64)
    s = Scheduler(cfg, params, n_slots=2, block_len=8, prefill_chunk=8,
                  gather_impl="pallas", kv_dtype="int8")
    assert s.engine.gather_impl == "pallas"
    prompt = np.arange(1, 10, dtype=np.int32)
    rid = s.submit(prompt, 4)
    out = s.drain()[rid]
    assert out == greedy_reference(cfg, params, prompt, 4)
    assert s.engine.allocator.in_use == 0


def test_chunked_vs_whole_prefill_pallas():
    """Chunk boundaries cannot change the kernel's math: a 29-token
    prompt prefilled in 8-token chunks streams the same greedy tokens
    as whole-prompt prefill (the ``generate`` reference IS the
    whole-prefill path), through the pallas gather."""
    cfg, params = setup()
    rng = np.random.default_rng(3)
    prompt = rng.integers(1, cfg.vocab_size, (29,)).astype(np.int32)
    ref = greedy_reference(cfg, params, prompt, 4)
    b = ContinuousBatcher(cfg, params, n_slots=1, prefill_bucket=8,
                          gather_impl="pallas")
    b.submit(prompt, 4)
    got = []
    while any(b.remaining > 0):
        got += [t for _s, t in b.step()]
    assert got == ref


# ---------------------------------------------------------------------------
# token-identical greedy streams (slow tier, like the r6 parity tests)
# ---------------------------------------------------------------------------


def _drive_batcher(b, prompts, budgets):
    got, slot_of, pending = {}, {}, list(range(len(prompts)))
    while pending or any(b.remaining > 0):
        while pending and b.free_slots():
            i = pending.pop(0)
            slot_of[i] = b.submit(prompts[i], budgets[i])
            got[i] = []
        for slot, token in b.step():
            req = next(i for i, s in slot_of.items()
                       if s == slot and len(got[i]) < budgets[i])
            got[req].append(token)
    return got


@pytest.mark.slow
@pytest.mark.parametrize("kv_heads", [None, 2])
def test_pallas_batcher_matches_dense_gather(kv_heads):
    """Staggered admissions, slot reuse, mixed budgets, MHA and GQA:
    the pallas gather must emit token-identical greedy streams to the
    dense gather over the same block pool."""
    cfg, params = setup(num_heads=4, num_kv_heads=kv_heads)
    rng = np.random.default_rng(1)
    prompts = [rng.integers(1, cfg.vocab_size, (l,)).astype(np.int32)
               for l in (7, 13, 4, 21)]
    budgets = [6, 10, 8, 5]
    dense = _drive_batcher(
        ContinuousBatcher(cfg, params, n_slots=2, prefill_bucket=8,
                          gather_impl="dense"),
        prompts, budgets,
    )
    pallas = _drive_batcher(
        ContinuousBatcher(cfg, params, n_slots=2, prefill_bucket=8,
                          gather_impl="pallas"),
        prompts, budgets,
    )
    assert dense == pallas


@pytest.mark.slow
@pytest.mark.parametrize("kv_heads,kv_dtype", [
    (None, None), (2, None), (2, "int8"),
])
def test_pallas_batcher_tp_matches_dense(kv_heads, kv_dtype):
    """TP=2 CPU mesh: the pallas kernel under shard_map (head-sharded
    pool AND head-sharded scale siblings for int8) matches the
    replicated DENSE-layout batcher token-for-token, GQA included."""
    from pytorch_distributed_tpu.parallel import make_mesh

    rep = tiny_config(attention="dense", max_seq_len=96, num_heads=4,
                      num_kv_heads=kv_heads)
    tpcfg = dataclasses.replace(rep, model_axis="model", tp_size=2)
    params = TransformerLM(rep).init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    mesh = make_mesh(jax.devices()[:2], data_parallel=1, seq_parallel=1,
                     model_parallel=2)
    rng = np.random.default_rng(2)
    prompts = [rng.integers(1, rep.vocab_size, (l,)).astype(np.int32)
               for l in (5, 11, 7)]
    budgets = [6, 6, 6]
    dense_rep = _drive_batcher(
        ContinuousBatcher(rep, params, n_slots=2, prefill_bucket=8,
                          cache_layout="dense"),
        prompts, budgets,
    )
    tp = ContinuousBatcher(tpcfg, params, n_slots=2, prefill_bucket=8,
                           mesh=mesh, gather_impl="pallas",
                           kv_dtype=kv_dtype)
    assert _drive_batcher(tp, prompts, budgets) == dense_rep
    # the pool — and for int8 its scale siblings — really are sharded
    leaves = jax.tree.leaves(tp.cache)
    pools = [x for x in leaves if x.ndim == 4]
    assert next(iter(pools[0].addressable_shards)).data.shape[2] == \
        pools[0].shape[2] // 2
    if kv_dtype == "int8":
        scales = [x for x in leaves if x.ndim == 3]
        assert scales, "int8 pool should carry scale leaves"
        assert next(iter(scales[0].addressable_shards)).data.shape[2] == \
            scales[0].shape[2] // 2
