"""Global-norm gradient clipping under every sharding (VERDICT r3 #2).

The reference never clipped (SGD ResNet, ``restnet_ddp.py:122``); an LM
framework must, and under this repo's shard_map steps the global norm is
only correct if each leaf's square-sum is psum'd over exactly the axes its
PartitionSpec shards (ops.optim.sharded_global_norm). These tests pin:

- norm parity with optax.global_norm on replicated trees;
- clipped-update parity of FSDP vs replicated DP (data-sharded leaves);
- clipped-update parity of TP(+SP) vs a single-device reference
  (Megatron-sharded leaves must psum over the model axis);
- clipped-update parity of PP vs the sequential microbatched reference
  (stage-stacked leaves must psum over the stage axis);
- fp16 scaler ordering: the clip threshold sees UNSCALED magnitudes
  (torch's scaler.unscale_-then-clip contract).

Each parity test clips hard (max_norm well below the true norm) so a
wrong norm — e.g. a missing cross-shard psum — would change every update
and blow past the tolerances.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from pytorch_distributed_tpu.models.resnet import BasicBlock, ResNet
from pytorch_distributed_tpu.models.transformer import tiny_config
from pytorch_distributed_tpu.ops.optim import (
    clip_by_global_norm,
    clip_grads_by_global_norm,
    sgd_with_weight_decay,
    sharded_global_norm,
)
from pytorch_distributed_tpu.parallel import (
    make_mesh,
    replicated_sharding,
    shard_batch,
    shard_fsdp_state,
)
from pytorch_distributed_tpu.train.lm import (
    create_lm_state,
    make_lm_train_step,
    shard_lm_state,
    shift_labels,
)
from pytorch_distributed_tpu.train.state import TrainState
from pytorch_distributed_tpu.train.step import make_train_step

CLIP = 0.05  # far below the true grad norms here -> always triggers


def test_sharded_global_norm_matches_optax_on_replicated():
    rng = np.random.default_rng(0)
    tree = {
        "a": jnp.asarray(rng.normal(size=(8, 16)), jnp.float32),
        "b": {"c": jnp.asarray(rng.normal(size=(5,)), jnp.float32)},
    }
    ours = float(sharded_global_norm(tree))
    ref = float(optax.global_norm(tree))
    np.testing.assert_allclose(ours, ref, rtol=1e-6)
    clipped, pre = clip_grads_by_global_norm(tree, 0.1)
    np.testing.assert_allclose(float(pre), ref, rtol=1e-6)
    np.testing.assert_allclose(
        float(optax.global_norm(clipped)), 0.1, rtol=1e-5
    )
    # under the threshold: identity
    same, _ = clip_grads_by_global_norm(tree, 1e9)
    jax.tree.map(
        lambda x, y: np.testing.assert_array_equal(np.asarray(x), np.asarray(y)),
        same, tree,
    )


def test_clip_transform_in_optax_chain_keeps_state_structure():
    tx_plain = sgd_with_weight_decay(0.1, momentum=0.9)
    tx_clip = optax.chain(clip_by_global_norm(CLIP), tx_plain)
    params = {"w": jnp.ones((4, 4))}
    # EmptyState prepended; the wrapped optimizer's state is untouched
    s_plain = tx_plain.init(params)
    s_clip = tx_clip.init(params)
    assert len(s_clip) == 2
    assert (jax.tree.structure(s_clip[1])
            == jax.tree.structure(s_plain))


# ---------------------------------------------------------------- FSDP

def _tiny_resnet():
    return ResNet(stage_sizes=(1, 1), block_cls=BasicBlock, num_classes=10,
                  num_filters=16)


def _image_batch(mesh, n=16, seed=0):
    rng = np.random.default_rng(seed)
    return shard_batch(mesh, {
        "image": rng.normal(size=(n, 16, 16, 3)).astype(np.float32),
        "label": rng.integers(0, 10, n).astype(np.int32),
    })


def test_clip_fsdp_matches_replicated(devices8):
    mesh = make_mesh(devices8)
    tx = sgd_with_weight_decay(0.1, momentum=0.9, weight_decay=1e-4)

    def run(fsdp, clip, steps=3):
        state = TrainState.create(_tiny_resnet(), tx, jax.random.key(0),
                                  (1, 16, 16, 3))
        if fsdp:
            state, specs = shard_fsdp_state(mesh, state)
        else:
            state = jax.device_put(state, replicated_sharding(mesh))
            specs = None
        step = make_train_step(mesh, state_specs=specs, grad_clip_norm=clip)
        for i in range(steps):
            state, _ = step(state, _image_batch(mesh, seed=i))
        return state

    state_f = run(True, CLIP)
    state_r = run(False, CLIP)
    flat_r = {str(p): v for p, v in
              jax.tree_util.tree_leaves_with_path(state_r.params)}
    for path, leaf in jax.tree_util.tree_leaves_with_path(state_f.params):
        np.testing.assert_allclose(
            np.asarray(leaf), np.asarray(flat_r[str(path)]),
            rtol=1e-4, atol=1e-6, err_msg=str(path),
        )
    # power check: the clip actually bit (an unclipped run differs)
    state_u = run(False, 0.0)
    diffs = [
        float(np.abs(np.asarray(a) - np.asarray(b)).max())
        for a, b in zip(jax.tree.leaves(state_u.params),
                        jax.tree.leaves(state_r.params))
    ]
    assert max(diffs) > 1e-4


# ------------------------------------------------------------------ TP

def _lm_batch(mesh, seed=0):
    rng = np.random.default_rng(seed)
    tokens = rng.integers(1, 128, (4, 32)).astype(np.int32)
    labels, weights = shift_labels(tokens)
    sh = NamedSharding(mesh, P("data", "seq"))
    return {
        "tokens": jax.device_put(tokens, sh),
        "labels": jax.device_put(labels, sh),
        "weights": jax.device_put(weights, sh),
    }


def test_clip_tp_matches_single_device(devices8):
    tx = sgd_with_weight_decay(0.1, momentum=0.9)

    def run(mesh, cfg, steps=3):
        state = create_lm_state(cfg, tx, jax.random.key(0), init_len=8)
        state, specs = shard_lm_state(mesh, state, cfg)
        step = make_lm_train_step(mesh, state_specs=specs, config=cfg,
                                  grad_clip_norm=CLIP)
        losses = []
        for i in range(steps):
            state, m = step(state, _lm_batch(mesh, seed=i))
            losses.append(float(m["loss"]))
        return state, losses, float(m["grad_norm"])

    mesh_tp = make_mesh(devices8, data_parallel=2, seq_parallel=2,
                        model_parallel=2)
    cfg_tp = tiny_config(attention="ring", model_axis="model", tp_size=2)
    mesh_1 = make_mesh(devices8[:1])
    cfg_1 = tiny_config(attention="dense")

    state_tp, losses_tp, gnorm_tp = run(mesh_tp, cfg_tp)
    state_1, losses_1, gnorm_1 = run(mesh_1, cfg_1)
    np.testing.assert_allclose(losses_tp, losses_1, rtol=5e-4)
    # the PRE-clip global norm itself must agree across shardings — this
    # is the direct probe of the cross-shard psum
    np.testing.assert_allclose(gnorm_tp, gnorm_1, rtol=5e-4)
    flat_1 = {str(p): v for p, v in
              jax.tree_util.tree_leaves_with_path(state_1.params)}
    for path, leaf in jax.tree_util.tree_leaves_with_path(state_tp.params):
        np.testing.assert_allclose(
            np.asarray(leaf), np.asarray(flat_1[str(path)]),
            rtol=2e-3, atol=3e-5, err_msg=str(path),
        )


# ------------------------------------------------------------------ PP

def test_clip_pp_matches_sequential_reference(devices8):
    from pytorch_distributed_tpu.train.pp import (
        create_pp_lm_state,
        make_pp_lm_train_step,
        make_pp_reference_step,
        shard_pp_state,
    )

    cfg = tiny_config(num_layers=4)
    tx = sgd_with_weight_decay(0.1, momentum=0.9)
    # the reference clips via the optax-chain form on replicated grads —
    # the independently-correct formulation
    tx_ref = optax.chain(clip_by_global_norm(CLIP),
                         sgd_with_weight_decay(0.1, momentum=0.9))
    mesh = make_mesh(devices8, data_parallel=2, seq_parallel=1,
                     model_parallel=4)
    n_stages = 4

    state0 = create_pp_lm_state(cfg, n_stages, tx, jax.random.key(0),
                                init_len=32)
    state_ref = create_pp_lm_state(cfg, n_stages, tx_ref, jax.random.key(0),
                                   init_len=32)
    state_pp, specs = shard_pp_state(mesh, state0)
    step_pp = make_pp_lm_train_step(mesh, cfg, specs, n_microbatches=2,
                                    grad_clip_norm=CLIP)
    step_ref = make_pp_reference_step(cfg, n_stages, tx_ref,
                                      n_microbatches=2)

    rng = np.random.default_rng(7)
    sh = NamedSharding(mesh, P("data"))
    for i in range(3):
        tokens = rng.integers(1, 128, (4, 32)).astype(np.int32)
        labels, weights = shift_labels(tokens)
        b = {"tokens": tokens, "labels": labels, "weights": weights}
        state_pp, m_pp = step_pp(
            state_pp, {k: jax.device_put(v, sh) for k, v in b.items()}
        )
        state_ref, m_ref = step_ref(state_ref, b)
        np.testing.assert_allclose(float(m_pp["loss"]), float(m_ref["loss"]),
                                   rtol=1e-4)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(jax.device_get(a)), np.asarray(b), rtol=2e-3,
            atol=2e-4,
        ),
        jax.device_get(state_pp.params), jax.device_get(state_ref.params),
    )


# ------------------------------------------------------- fp16 scaler

def test_clip_sees_unscaled_grads(devices8):
    """torch contract: scaler.unscale_() THEN clip. With a 2^8 loss scale
    (exact in fp32), a scaled-and-unscaled run must track the scalerless
    run bit-closely — if the clip saw scaled magnitudes its threshold
    would bite 256x harder and the trajectories would diverge."""
    from pytorch_distributed_tpu.ops.precision import DynamicLossScaler

    mesh = make_mesh(devices8)
    tx = sgd_with_weight_decay(0.1, momentum=0.9)

    def run(scaler, steps=3):
        state = TrainState.create(_tiny_resnet(), tx, jax.random.key(0),
                                  (1, 16, 16, 3), scaler=scaler)
        state = jax.device_put(state, replicated_sharding(mesh))
        step = make_train_step(mesh, grad_clip_norm=CLIP)
        for i in range(steps):
            state, m = step(state, _image_batch(mesh, seed=i))
            assert float(m["grads_finite"]) == 1.0
        return state

    state_s = run(DynamicLossScaler.create(init_scale=2.0**8))
    state_p = run(None)
    for a, b in zip(jax.tree.leaves(state_s.params),
                    jax.tree.leaves(state_p.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-7)
