"""FSDP/ZeRO for the LM trainer (round 4): leaves the TP/EP rules leave
replicated shard over the data axis at rest; the step all_gathers them
before the forward and reduce-scatters their grads with the LM's
sum-convention combine. Parity with the non-FSDP path is the whole
contract — plus the memory win and composition with TP/SP/EP/clipping."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

pytestmark = pytest.mark.slow

from pytorch_distributed_tpu.models.transformer import tiny_config
from pytorch_distributed_tpu.ops.optim import sgd_with_weight_decay
from pytorch_distributed_tpu.parallel import make_mesh
from pytorch_distributed_tpu.train.lm import (
    create_lm_state,
    empty_lm_metrics,
    lm_fsdp_membership,
    make_lm_eval_step,
    make_lm_train_step,
    shard_lm_state,
    shift_labels,
)
from pytorch_distributed_tpu.train.lm_trainer import shard_lm_batch


def batch(mesh, seed=0, b=4, l=32):
    rng = np.random.default_rng(seed)
    tokens = rng.integers(1, 128, (b, l)).astype(np.int32)
    labels, weights = shift_labels(tokens)
    return shard_lm_batch(
        mesh, {"tokens": tokens, "labels": labels, "weights": weights}
    )


def run(mesh, cfg, fsdp, steps=3, clip=0.0, tx=None):
    tx = tx or sgd_with_weight_decay(0.1, momentum=0.9)
    state = create_lm_state(cfg, tx, jax.random.key(0), init_len=8)
    state, specs = shard_lm_state(mesh, state, cfg, fsdp=fsdp)
    step = make_lm_train_step(mesh, state_specs=specs, config=cfg,
                              fsdp=fsdp, grad_clip_norm=clip)
    losses = []
    for i in range(steps):
        state, m = step(state, batch(mesh, seed=i))
        losses.append(float(m["loss"]))
    return state, specs, losses


def assert_params_match(state_a, state_b, rtol=1e-4, atol=1e-6):
    flat_b = {str(p): v for p, v in
              jax.tree_util.tree_leaves_with_path(state_b.params)}
    for path, leaf in jax.tree_util.tree_leaves_with_path(state_a.params):
        np.testing.assert_allclose(
            np.asarray(leaf), np.asarray(flat_b[str(path)]),
            rtol=rtol, atol=atol, err_msg=str(path),
        )


def test_lm_fsdp_matches_replicated(devices8):
    mesh = make_mesh(devices8, data_parallel=4, seq_parallel=2)
    cfg = tiny_config(attention="ring")
    state_f, specs, losses_f = run(mesh, cfg, fsdp=True)
    state_r, _, losses_r = run(mesh, cfg, fsdp=False)
    np.testing.assert_allclose(losses_f, losses_r, rtol=1e-4)
    assert_params_match(state_f, state_r)
    # the memory win is real: at least the big matrices are data-sharded
    gather = lm_fsdp_membership(state_f.params, mesh, cfg)
    n_sharded = sum(jax.tree.leaves(gather))
    # tp=1 mesh: the Megatron rules are vacuous here, so the big block
    # matrices fall through to ZeRO along with wte/wpe/lm_head
    assert n_sharded >= 6, n_sharded
    flat_specs = {str(p): v for p, v in
                  jax.tree_util.tree_leaves_with_path(specs.params)}
    flat_gather = {str(p): v for p, v in
                   jax.tree_util.tree_leaves_with_path(gather)}
    for path, leaf in jax.tree_util.tree_leaves_with_path(state_f.params):
        if not flat_gather[str(path)]:
            continue
        spec = flat_specs[str(path)]
        d = next(i for i, part in enumerate(spec) if part is not None)
        assert {s.data.shape[d] for s in leaf.addressable_shards} == {
            leaf.shape[d] // 4
        }, path


def test_lm_fsdp_composes_with_tp(devices8):
    mesh = make_mesh(devices8, data_parallel=2, seq_parallel=2,
                     model_parallel=2)
    cfg = tiny_config(attention="ring", model_axis="model", tp_size=2)
    state_f, specs, losses_f = run(mesh, cfg, fsdp=True)
    state_r, specs_r, losses_r = run(mesh, cfg, fsdp=False)
    np.testing.assert_allclose(losses_f, losses_r, rtol=1e-4)
    assert_params_match(state_f, state_r, rtol=2e-4, atol=2e-6)
    # TP leaves keep their Megatron placement (never double-sharded over
    # data by the overlay; never gathered)
    gather = lm_fsdp_membership(state_f.params, mesh, cfg)
    qkv_spec = specs.params["block0"]["attn"]["qkv"]["kernel"]
    assert qkv_spec == specs_r.params["block0"]["attn"]["qkv"]["kernel"]
    assert not gather["block0"]["attn"]["qkv"]["kernel"]


def test_lm_fsdp_with_ep_moe(devices8):
    mesh = make_mesh(devices8, data_parallel=4, seq_parallel=2)
    cfg = tiny_config(
        attention="ring", n_experts=4, moe_every=2,
        capacity_factor=float(4 * 8), moe_aux_weight=0.0,
        expert_axis="data", ep_size=4,
    )
    state_f, specs, losses_f = run(mesh, cfg, fsdp=True)
    state_r, _, losses_r = run(mesh, cfg, fsdp=False)
    np.testing.assert_allclose(losses_f, losses_r, rtol=5e-4)
    assert_params_match(state_f, state_r, rtol=2e-3, atol=3e-5)
    # expert leaves stay EP shards (data axis), NOT gather targets
    gather = lm_fsdp_membership(state_f.params, mesh, cfg)
    assert not gather["block1"]["moe"]["w_up"]


def test_lm_fsdp_with_grad_clip(devices8):
    """sharded_global_norm over the MIXED spec tree (FSDP + replicated
    leaves) must equal the replicated run's clipped trajectory."""
    mesh = make_mesh(devices8, data_parallel=4, seq_parallel=2)
    cfg = tiny_config(attention="ring")
    state_f, _, losses_f = run(mesh, cfg, fsdp=True, clip=0.05)
    state_r, _, losses_r = run(mesh, cfg, fsdp=False, clip=0.05)
    np.testing.assert_allclose(losses_f, losses_r, rtol=1e-4)
    assert_params_match(state_f, state_r)


def test_lm_fsdp_eval_matches(devices8):
    mesh = make_mesh(devices8, data_parallel=4, seq_parallel=2)
    cfg = tiny_config(attention="ring")
    tx = sgd_with_weight_decay(0.1)

    def evaluate(fsdp):
        state = create_lm_state(cfg, tx, jax.random.key(0), init_len=8)
        state, specs = shard_lm_state(mesh, state, cfg, fsdp=fsdp)
        ev = make_lm_eval_step(mesh, state_specs=specs, config=cfg,
                               fsdp=fsdp)
        acc = jax.device_put(empty_lm_metrics(), NamedSharding(mesh, P()))
        acc = jax.device_get(ev(state, batch(mesh, seed=9), acc))
        return float(acc["loss_sum"]) / float(acc["tokens"])

    np.testing.assert_allclose(evaluate(True), evaluate(False), rtol=1e-5)


def test_lm_fsdp_requires_specs():
    mesh = make_mesh(jax.devices("cpu")[:1])
    with pytest.raises(ValueError, match="fsdp=True needs state_specs"):
        make_lm_train_step(mesh, fsdp=True)


def test_lm_fsdp_trainer_suspend_resume_bit_parity(tmp_path, devices8):
    """The full trainer integration: an FSDP+TP LM run interrupted by a
    suspend and resumed (sharded checkpoint of the MIXED spec tree —
    ZeRO shards + Megatron shards + replicated leaves) equals the
    uninterrupted run bit for bit."""
    from conftest import FireAtStep
    from pytorch_distributed_tpu.data.tokens import SyntheticTokens
    from pytorch_distributed_tpu.train import LMTrainer, LMTrainerConfig

    def trainer(save_dir, watcher=None):
        mesh = make_mesh(devices8, data_parallel=2, seq_parallel=2,
                         model_parallel=2)
        cfg = LMTrainerConfig(epochs=2, batch_size=2, lr=1e-2,
                              save_dir=str(save_dir), num_workers=0,
                              log_every=1, fsdp=True, grad_clip_norm=1.0)
        model_cfg = tiny_config(attention="ring", model_axis="model",
                                tp_size=2, dropout=0.1)
        train = SyntheticTokens(size=16, seq_len=32, vocab_size=128)
        val = SyntheticTokens(size=8, seq_len=32, vocab_size=128, seed=9)
        return LMTrainer(model_cfg, train, val, cfg, mesh=mesh,
                         suspend_watcher=watcher)

    t_ref = trainer(tmp_path / "ref")
    t_ref.fit()

    t_int = trainer(tmp_path / "int", watcher=FireAtStep(7))
    with pytest.raises(SystemExit):
        t_int.fit()
    assert t_int.ckpt.has_latest()

    t_res = trainer(tmp_path / "int")
    t_res.fit()
    assert_params_match(t_res.state, t_ref.state, rtol=0, atol=0)
    assert int(jax.device_get(t_ref.state.step)) == int(
        jax.device_get(t_res.state.step)
    )
