"""ResNet-50/ImageNet, DDP + mixed precision — ≙ ``resnet_ddp_apex.py`` (R4).

The reference runs fp16 under ``torch.cuda.amp.autocast`` with a dynamic
loss scaler (``resnet_ddp_apex.py:27-33,107``) — its fastest config
(230.98 s/epoch, BASELINE.md). On TPU mixed precision is bf16 on the MXU:
fp32-range exponent means no scaler is needed, so "AMP" here is just the
bf16 compute policy on the same trainer (pass precision=fp16 via code to get
a real dynamic-scaler run for parity experiments).

    MASTER_IP=… MASTER_PORT=… WORLD_SIZE=<hosts> RANK=<host_idx> \
        python recipes/resnet_ddp_amp.py      # on every host
"""

from common import parse_args, run  # noqa: E402  (bootstraps sys.path)

import pytorch_distributed_tpu as pdt

pdt.set_env("202607")

from pytorch_distributed_tpu.parallel import init_process_group, make_mesh  # noqa: E402


if __name__ == "__main__":
    args = parse_args(__doc__)
    init_process_group()
    run(args, make_mesh(), precision="bf16")
