"""ResNet-50/ImageNet on a single chip — ≙ ``resnet_single_gpu.py`` (R1).

fp32 baseline: one device, bs 400, SGD(0.1, momentum 0.9, wd 1e-4),
StepLR(30, 0.1), 100 epochs, per-epoch validation, suspend/resume
(``resnet_single_gpu.py:69-134``). Same trainer as every other recipe; the
mesh is just one chip.

    python recipes/resnet_single.py [--synthetic] [--tiny]
"""

from common import parse_args, run  # noqa: E402  (bootstraps sys.path)

import pytorch_distributed_tpu as pdt

pdt.set_env("202607")  # ≙ hf_env.set_env('202111'), every ref script lines 1-2

from pytorch_distributed_tpu.parallel import single_device_mesh  # noqa: E402


if __name__ == "__main__":
    args = parse_args(__doc__)
    run(args, single_device_mesh(), precision="fp32")
