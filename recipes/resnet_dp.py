"""ResNet-50/ImageNet, single-process data parallel — ≙ ``resnet_dp.py`` (R2).

The reference's ``nn.DataParallel`` replicates the model and scatters a
global batch of 3200 every step from one process (``resnet_dp.py:69,77,82``)
— the design that capped it at 1.81× on 8 GPUs (59.8 % util, BASELINE.md).
On TPU the same "one process, all local chips" topology is just a local
mesh: the compiled step is SPMD, nothing is scattered or re-replicated per
step, so this recipe scales like DDP while keeping DP's launch ergonomics.

    python recipes/resnet_dp.py [--synthetic] [--tiny]
"""

from common import parse_args, run  # noqa: E402  (bootstraps sys.path)

import pytorch_distributed_tpu as pdt

pdt.set_env("202607")

from pytorch_distributed_tpu.parallel import local_mesh  # noqa: E402


if __name__ == "__main__":
    args = parse_args(__doc__)
    run(args, local_mesh(), precision="fp32")
