"""Transformer LM pretraining over a (data, seq, model) mesh.

Beyond the reference's capability surface (it has no attention model,
SURVEY.md §5 long-context ABSENT) but a first-class recipe here: the same
zero-required-args ergonomics, trainer contracts (suspend/resume,
latest/best checkpoints, JSONL metrics), and env rendezvous as the ResNet
recipes, driving ``LMTrainer`` with ring-attention sequence parallelism
and optional tensor parallelism.

    python recipes/lm_pretrain.py --tiny            # CPU smoke (8 virtual devices)
    python recipes/lm_pretrain.py --tokens corpus.npy --seq-len 2048
    MASTER_IP=… WORLD_SIZE=… RANK=… python recipes/lm_pretrain.py   # pod

The mesh factors the device count as dp×sp×tp from --seq-parallel /
--model-parallel (default: sequence parallelism on, tp off). Token data is
a flat int array (.npy or memmap-able raw int32) windowed to --seq-len;
--synthetic generates deterministic fake tokens.
"""

from common import parse_lm_args  # noqa: E402  (bootstraps sys.path)

import pytorch_distributed_tpu as pdt

pdt.set_env("202607")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from pytorch_distributed_tpu.models.transformer import (  # noqa: E402
    TransformerConfig,
    tiny_config,
)
from pytorch_distributed_tpu.parallel import (  # noqa: E402
    global_batch_size,
    init_process_group,
    make_mesh,
)
from pytorch_distributed_tpu.train import LMTrainer, LMTrainerConfig  # noqa: E402
from pytorch_distributed_tpu.utils.logging import rank0_print  # noqa: E402
from pytorch_distributed_tpu.utils.suspend import SuspendWatcher  # noqa: E402


def build_token_datasets(args):
    if args.synthetic or args.tiny:
        from pytorch_distributed_tpu.data import SyntheticTokens

        vocab = 128 if args.tiny else args.vocab_size
        seq = 32 if args.tiny else args.seq_len
        n = 64 if args.tiny else 4096
        return (
            SyntheticTokens(n, seq, vocab),
            SyntheticTokens(max(n // 8, 8), seq, vocab, seed=1),
            seq,
            vocab,
        )
    import numpy as np

    from pytorch_distributed_tpu.data import TokenArrayDataset

    if not args.tokens:
        raise SystemExit("--tokens <corpus.npy> required without --synthetic")
    tokens = np.load(args.tokens, mmap_mode="r")
    n_val = max(len(tokens) // 100, args.seq_len)
    return (
        TokenArrayDataset(tokens[:-n_val], args.seq_len),
        TokenArrayDataset(tokens[-n_val:], args.seq_len),
        args.seq_len,
        args.vocab_size,
    )


def main() -> None:
    args = parse_lm_args(__doc__)
    init_process_group()
    train_ds, val_ds, seq_len, vocab = build_token_datasets(args)

    sp = args.seq_parallel
    tp = args.model_parallel
    if args.pipeline_stages < 0:
        raise SystemExit(
            f"--pipeline-stages must be >= 1 (or 0 = off), got "
            f"{args.pipeline_stages}"
        )
    if args.pipeline_stages:
        # PP rides the model axis (stages); the batch shards over data
        # only, so seq-parallel (default 2) is overridden to 1. The TP
        # degree must stay 1 — the model config must NOT get a
        # model_axis; only the MESH carries the stage-sized axis
        # (TP-within-PP needs the train.pp API with a dedicated stage
        # axis).
        if tp > 1:
            raise SystemExit(
                "--pipeline-stages uses the model axis for stages; drop "
                "--model-parallel (TP-within-PP needs the train.pp API "
                "with a dedicated stage axis)"
            )
        if sp > 1:
            rank0_print(
                f"pipeline run: overriding --seq-parallel {sp} -> 1 "
                "(PP batches shard over data only)"
            )
        sp = 1
    mesh_mp = args.pipeline_stages or tp
    n = jax.device_count()
    if n % (sp * mesh_mp):
        raise SystemExit(
            f"{n} devices not divisible by sp*mp={sp * mesh_mp}"
        )
    mesh = make_mesh(data_parallel=n // (sp * mesh_mp), seq_parallel=sp,
                     model_parallel=mesh_mp)

    # seq-sharded runs need a global (ring) attention; honor an explicit
    # ring variant from --attention, otherwise default to the Pallas-kernel
    # ring (ops/ring_flash.py — ~2.6x the XLA ring end-to-end, BENCH_LM.md)
    if sp > 1:
        attention = (args.attention
                     if args.attention in ("ring", "ring_flash")
                     else "ring_flash")
    else:
        attention = args.attention
    if args.tiny:
        model_cfg = tiny_config(
            # tiny exists for CPU smoke runs, where the Pallas kernels
            # can't compile: pin the XLA paths
            attention="ring" if sp > 1 else "dense",
            model_axis="model" if tp > 1 else None,
            tp_size=tp,
            vocab_parallel=args.vocab_parallel,
            dropout=args.dropout,
            ring_layout=args.ring_layout if sp > 1 else "contiguous",
        )
    else:
        model_cfg = TransformerConfig(
            vocab_size=vocab,
            num_layers=args.layers,
            num_heads=args.heads,
            num_kv_heads=args.kv_heads,
            pos_embedding=args.pos_embedding,
            embed_dim=args.embed_dim,
            max_seq_len=seq_len,
            dropout=args.dropout,
            dtype=jnp.bfloat16,
            attention=attention,
            model_axis="model" if tp > 1 else None,
            tp_size=tp,
            vocab_parallel=args.vocab_parallel,
            ring_layout=args.ring_layout if sp > 1 else "contiguous",
        )
    if args.vocab_parallel and args.pipeline_stages:
        raise SystemExit(
            "--vocab-parallel does not compose with --pipeline-stages "
            "(PPEmbed/PPHead are stage-replicated; train/pp.py)"
        )
    if args.vocab_parallel and tp <= 1:
        raise SystemExit("--vocab-parallel needs --model-parallel > 1")
    if args.save_every_n_steps < 0:
        raise SystemExit(
            f"--save-every-n-steps must be >= 0 (0 = off), got "
            f"{args.save_every_n_steps}"
        )
    if args.keep_last_ckpts < 1:
        raise SystemExit(
            f"--keep-last-ckpts must be >= 1, got {args.keep_last_ckpts}"
        )

    cfg = LMTrainerConfig(
        epochs=args.epochs if args.epochs is not None else (2 if args.tiny else 1),
        batch_size=args.batch_size if args.batch_size is not None
        else (2 if args.tiny else 8),
        lr=args.lr,
        warmup_steps=0 if args.tiny else 2000,
        save_dir=args.save_dir,
        num_workers=0 if args.tiny else 4,
        grad_clip_norm=args.grad_clip_norm,
        fsdp=args.fsdp,
        pipeline_stages=args.pipeline_stages,
        pp_microbatches=args.pp_microbatches,
        save_every_n_steps=args.save_every_n_steps,
        keep_last_ckpts=args.keep_last_ckpts,
        nan_guard=args.nan_guard,
        max_bad_steps=args.max_bad_steps,
        watchdog_timeout_s=args.watchdog_timeout,
        metrics_out=args.metrics_out,
        trace_dir=args.trace_dir,
        flush_every=args.flush_every,
        compile_cache_dir=args.compile_cache_dir,
        warmup=args.warmup,
        cost_cards=args.cost_cards,
        anomaly_threshold=args.anomaly_threshold,
        metrics_port=args.metrics_port,
    )
    trainer = LMTrainer(model_cfg, train_ds, val_ds, cfg, mesh=mesh,
                        suspend_watcher=SuspendWatcher())
    rank0_print(
        f"devices: {jax.device_count()} ({jax.process_count()} hosts), "
        f"mesh {dict(mesh.shape)}, global batch "
        f"{global_batch_size(mesh, cfg.batch_size)} seqs × {seq_len} tokens, "
        f"attention {model_cfg.attention}, tp {tp}"
    )
    summary = trainer.fit()
    rank0_print(f"done: best ppl {summary.get('best_ppl', float('inf')):.3f}")


if __name__ == "__main__":
    main()
