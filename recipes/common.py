"""Shared recipe scaffolding.

The reference implements its epoch/val/suspend loop four times (SURVEY.md
§2a R1-R4); here each recipe is a Mesh + a TrainerConfig over the one SPMD
trainer. This module holds the pieces every recipe shares: the hardcoded
reference hyperparameters (``restnet_ddp.py:77-83``), dataset construction
(real TPRC ImageNet or the synthetic stand-in), and the run function.

Recipes keep the reference's zero-required-args ergonomics (`python
recipes/resnet_ddp.py`); ``--synthetic`` / ``--tiny`` exist so every recipe
also runs as a smoke test on a laptop CPU (SURVEY.md §4 — the reference can
only validate on its real cluster; we refuse to inherit that).
"""

from __future__ import annotations

import argparse
import os
import sys

# `python recipes/<recipe>.py` puts recipes/ (not the repo root) on sys.path;
# make the package importable without an install.
_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

# Pin the environment BEFORE jax is imported: jax binds env-var-driven config
# defaults (e.g. JAX_COMPILATION_CACHE_DIR, which set_env establishes) at
# import time. The recipes' own set_env calls then find it already active.
from pytorch_distributed_tpu.utils.env import set_env

set_env("202607")

import jax

if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
    # A site TPU plugin may force its own platform list into the jax config,
    # overriding JAX_PLATFORMS; honor the caller's explicit CPU request so
    # --xla_force_host_platform_device_count virtual devices are visible.
    jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp

from pytorch_distributed_tpu.models import resnet50
from pytorch_distributed_tpu.models.resnet import BasicBlock, ResNet
from pytorch_distributed_tpu.parallel import global_batch_size
from pytorch_distributed_tpu.train import Trainer, TrainerConfig
from pytorch_distributed_tpu.utils.logging import rank0_print
from pytorch_distributed_tpu.utils.suspend import SuspendWatcher


def _base_parser(description: str, save_dir: str,
                 batch_help: str) -> argparse.ArgumentParser:
    """Flags every recipe shares — one definition, no drift."""
    p = argparse.ArgumentParser(description=description)
    p.add_argument("--synthetic", action="store_true",
                   help="synthetic data instead of on-disk records")
    p.add_argument("--tiny", action="store_true",
                   help="tiny model/epochs for smoke-testing on CPU")
    p.add_argument("--save-dir", default=save_dir, help="checkpoint directory")
    p.add_argument("--epochs", type=int, default=None)
    p.add_argument("--batch-size", type=int, default=None, help=batch_help)
    # Resilience guards (resilience/; ANALYSIS.md "Failure model &
    # recovery guarantees"). Example — survive NaN spikes and hangs on a
    # long run:
    #   python recipes/lm_pretrain.py --tiny --nan-guard --max-bad-steps 5 \
    #       --watchdog-timeout 600 --save-every-n-steps 500
    p.add_argument("--nan-guard", action="store_true",
                   help="compile a finite gate into the train step: a "
                        "non-finite loss/grad step keeps the pre-step "
                        "params on device (no host sync) instead of "
                        "poisoning the run")
    p.add_argument("--max-bad-steps", type=int, default=0,
                   help="with --nan-guard: after this many CONSECUTIVE "
                        "skipped steps, roll back to the last good "
                        "checkpoint (0 = skip-only, never roll back)")
    p.add_argument("--watchdog-timeout", type=float, default=0.0,
                   help="seconds without a completed step before the "
                        "watchdog dumps all-thread stacks and latches "
                        "the suspend (checkpoint-and-yield) path "
                        "(0 = off)")
    # Telemetry (telemetry/; ANALYSIS.md "Observability & goodput").
    # Example — sync-free metrics + spans + a goodput report:
    #   python recipes/lm_pretrain.py --tiny --flush-every 8 \
    #       --metrics-out run.jsonl --trace-dir traces/
    #   python scripts/telemetry_report.py run.jsonl
    p.add_argument("--metrics-out", default=None,
                   help="JSONL metrics stream path (default "
                        "<save-dir>/metrics.jsonl; rank-0 only). Render "
                        "with scripts/telemetry_report.py — train series, "
                        "epoch timing, and the run's goodput breakdown")
    p.add_argument("--trace-dir", default=None,
                   help="write the host span Chrome trace (data_wait/"
                        "step_dispatch/ckpt_save/...) to "
                        "<dir>/spans.trace.json; spans also mirror into "
                        "jax.profiler annotations when PDT_TRACE_DIR "
                        "captures an xprof trace")
    p.add_argument("--flush-every", type=int, default=32,
                   help="device metrics ring window: log-interval metric "
                        "scalars accumulate on device and drain with one "
                        "lagged transfer per window — logging never "
                        "blocks the dispatch pipeline (0 = legacy "
                        "blocking float() sync per log interval)")
    # Compile cache (compilecache/; ANALYSIS.md "Cold start & compile
    # cache"). Example — a preemption-resumed run that reloads its step
    # executables from disk instead of recompiling:
    #   python recipes/lm_pretrain.py --tiny --warmup \
    #       --compile-cache-dir /shared/pdt_cache
    # (or point every job at one cache: export PDT_COMPILE_CACHE_DIR=...)
    p.add_argument("--compile-cache-dir", default=None,
                   help="persistent XLA compilation cache directory (env "
                        "fallback PDT_COMPILE_CACHE_DIR): a relaunched or "
                        "preemption-resumed run with the same fingerprint "
                        "loads executables from disk instead of "
                        "recompiling")
    p.add_argument("--warmup", action="store_true",
                   help="AOT-compile the run's program registry (train + "
                        "eval step) before step 1 — with a populated "
                        "--compile-cache-dir the goodput compile fraction "
                        "collapses; kind=\"warmup\" manifest records land "
                        "in the metrics JSONL")
    # Attribution & forensics (telemetry/; ANALYSIS.md "Performance
    # attribution & forensics"). Example — flag a wedged step and leave a
    # readable event ring behind:
    #   PDT_FAULT_PLAN='{"faults":[{"site":"train.step","kind":"hang",
    #       "at":10,"seconds":2}]}' python recipes/lm_pretrain.py --tiny \
    #       --metrics-out run.jsonl --cost-cards
    #   python scripts/telemetry_report.py run.jsonl   # anomaly + roofline
    p.add_argument("--cost-cards", action="store_true",
                   help="emit kind=\"program_cost\" records at fit end: "
                        "per-program FLOPs/bytes from the compiler joined "
                        "with measured step time into MFU and a "
                        "compute-vs-bandwidth roofline class (one extra "
                        "AOT compile per program, cache-hit when "
                        "--compile-cache-dir is set)")
    p.add_argument("--anomaly-threshold", type=float, default=8.0,
                   help="robust z-score bound for the streaming anomaly "
                        "sentinel over step-time/data-wait series "
                        "(kind=\"anomaly\" JSONL with context; 0 = off)")
    p.add_argument("--metrics-port", type=int, default=None,
                   help="serve live Prometheus-text /metrics on this "
                        "port (stdlib HTTP thread; 0 = ephemeral); "
                        "scripts/pdt_top.py is the JSONL-tailing twin")
    return p


def parse_args(description: str) -> argparse.Namespace:
    p = _base_parser(description, save_dir="output",
                     batch_help="per-replica batch size (ref default 400)")
    p.add_argument("--data-dir", default=None, help="TPRC ImageNet directory")
    p.add_argument("--raw", action="store_true",
                   help="use the decode-free raw split (<data-dir>/"
                        "{train,val}.rawtprc; pack with "
                        "scripts/pack_imagenet.py --raw)")
    p.add_argument("--raw-aug", default="rrc", choices=["rrc", "crop"],
                   help="raw-split train augmentation: rrc keeps the "
                        "reference's RandomResizedCrop semantics (applied "
                        "to the stored 256px image); crop is the classic "
                        "random-crop+flip — ~3x faster per core but a "
                        "different training distribution")
    return p.parse_args()


def build_datasets(args):
    if args.synthetic or args.tiny:
        from pytorch_distributed_tpu.data import SyntheticImageClassification

        size = 16 if args.tiny else 224
        n_train, n_val = (256, 64) if args.tiny else (8192, 1024)
        classes = 10 if args.tiny else 1000
        return (
            SyntheticImageClassification(n_train, size, classes),
            SyntheticImageClassification(n_val, size, classes, seed=1),
            size,
            classes,
        )
    from pytorch_distributed_tpu.data.imagenet import DEFAULT_DATA_DIR, ImageNet

    data_dir = args.data_dir or DEFAULT_DATA_DIR
    if getattr(args, "raw", False):
        # decode-free fast path (pre-decoded uint8 records, native C
        # batch collate, device-side normalization): ~10-30x the JPEG
        # loader's throughput per core — scripts/bench_data.py. Pack with
        # scripts/pack_imagenet.py --raw.
        from pytorch_distributed_tpu.data import RawImageNet

        return (
            RawImageNet("train", data_dir=data_dir,
                        aug=getattr(args, "raw_aug", "rrc")),
            RawImageNet("val", data_dir=data_dir, aug="none"),
            224,
            1000,
        )
    # ref: hfai.datasets.ImageNet('train'/'val', transform), restnet_ddp.py:107,117
    return (
        ImageNet("train", data_dir=data_dir),
        ImageNet("val", data_dir=data_dir),
        224,
        1000,
    )


def build_model(args, num_classes: int, precision: str):
    dtype = jnp.bfloat16 if precision == "bf16" else jnp.float32
    if args.tiny:
        return ResNet(stage_sizes=(1, 1), block_cls=BasicBlock,
                      num_classes=num_classes, num_filters=8, dtype=dtype)
    # ref: torchvision.models.resnet50(), restnet_ddp.py:98
    return resnet50(num_classes=num_classes, dtype=dtype)


def run(args, mesh, precision: str = "fp32") -> dict:
    """Build everything and fit — the body shared by all four recipes."""
    train_ds, val_ds, image_size, num_classes = build_datasets(args)
    model = build_model(args, num_classes, precision)
    cfg = TrainerConfig(
        # ref hyperparameters: restnet_ddp.py:77-83, resnet_single_gpu.py:107-109
        epochs=args.epochs if args.epochs is not None else (2 if args.tiny else 100),
        batch_size=args.batch_size if args.batch_size is not None else (4 if args.tiny else 400),
        lr=0.1 if not args.tiny else 0.05,
        momentum=0.9,
        weight_decay=1e-4,
        lr_step_epochs=30,
        lr_gamma=0.1,
        precision=precision,
        save_dir=args.save_dir,
        num_workers=0 if args.tiny else 8,
        nan_guard=args.nan_guard,
        max_bad_steps=args.max_bad_steps,
        watchdog_timeout_s=args.watchdog_timeout,
        metrics_out=args.metrics_out,
        trace_dir=args.trace_dir,
        flush_every=args.flush_every,
        compile_cache_dir=args.compile_cache_dir,
        warmup=args.warmup,
        cost_cards=args.cost_cards,
        anomaly_threshold=args.anomaly_threshold,
        metrics_port=args.metrics_port,
    )
    trainer = Trainer(
        model,
        train_ds,
        val_ds,
        cfg,
        mesh=mesh,
        suspend_watcher=SuspendWatcher(),
        input_shape=(1, image_size, image_size, 3),
    )
    rank0_print(
        f"devices: {jax.device_count()} ({jax.process_count()} hosts), "
        f"mesh {dict(mesh.shape)}, global batch "
        f"{global_batch_size(mesh, cfg.batch_size)}, precision {precision}"
    )
    summary = trainer.fit()
    rank0_print(f"done: best acc1 {summary.get('best_acc', 0.0):.2f}")
    return summary


def parse_lm_args(description: str) -> argparse.Namespace:
    """Arguments for the LM pretraining recipe (recipes/lm_pretrain.py)."""
    p = _base_parser(description, save_dir="output_lm",
                     batch_help="sequences per data-replica step")
    p.add_argument("--tokens", default=None,
                   help="flat int token array (.npy), windowed to --seq-len")
    p.add_argument("--seq-len", type=int, default=2048)
    p.add_argument("--vocab-size", type=int, default=32000)
    p.add_argument("--layers", type=int, default=12)
    p.add_argument("--heads", type=int, default=12)
    p.add_argument("--kv-heads", type=int, default=None,
                   help="grouped-query attention: K/V head count (must "
                        "divide --heads; default = MHA). Shrinks the "
                        "decode KV cache and kv projection by the group "
                        "factor")
    p.add_argument("--pos-embedding", default="learned",
                   choices=["learned", "rope"],
                   help="position encoding: GPT-2-style learned wpe table "
                        "or rotary (q/k rotation in attention, no table)")
    p.add_argument("--embed-dim", type=int, default=768)
    p.add_argument("--dropout", type=float, default=0.0)
    p.add_argument("--lr", type=float, default=3e-4)
    p.add_argument("--grad-clip-norm", type=float, default=0.0,
                   help="global-norm gradient clip (0 = off, the pre-r4 "
                        "behavior so published trajectories stay "
                        "reproducible; 1.0 is the usual LM setting). The "
                        "norm is sharding-correct under TP/SP/FSDP "
                        "(ops.optim.sharded_global_norm)")
    p.add_argument("--attention", default="flash",
                   choices=["dense", "blockwise", "flash", "ring",
                            "ring_flash"],
                   help="attention path (seq-sharded runs default to "
                        "ring_flash; pass ring for the XLA ring)")
    p.add_argument("--seq-parallel", type=int, default=2,
                   help="sequence-parallel degree (ring attention when > 1)")
    p.add_argument("--ring-layout", default="contiguous",
                   choices=["contiguous", "zigzag"],
                   help="causal-ring shard layout; zigzag balances the "
                        "causal critical path across seq shards "
                        "(parallel/sequence.py)")
    p.add_argument("--fsdp", action="store_true",
                   help="ZeRO-shard replicated params/optimizer over the "
                        "data axis (gather/scatter in the step; composes "
                        "with TP/EP/SP)")
    p.add_argument("--pipeline-stages", type=int, default=0,
                   help="train through the GPipe pipeline with this many "
                        "stages on the model axis (0 = off; excludes "
                        "--model-parallel/--seq-parallel)")
    p.add_argument("--pp-microbatches", type=int, default=8,
                   help="GPipe microbatches per step (clamped to the "
                        "per-shard batch; 8 is the measured default, "
                        "BENCH_PP.md)")
    p.add_argument("--model-parallel", type=int, default=1,
                   help="tensor-parallel degree")
    p.add_argument("--vocab-parallel", action="store_true",
                   help="Megatron vocab parallelism: shard wte + lm_head "
                        "vocab dims over the TP axis (needs "
                        "--model-parallel > 1; ~-44%% per-device state at "
                        "tp=2, BENCH_LM.md r5)")
    p.add_argument("--save-every-n-steps", type=int, default=0,
                   help="step-interval durability: non-blocking sharded "
                        "step-<N>.ckpt saves every N steps (0 = off, the "
                        "reference's suspend/best-only policy)")
    p.add_argument("--keep-last-ckpts", type=int, default=3,
                   help="retention for --save-every-n-steps (completed "
                        "checkpoints kept; resume picks the newest)")
    return p.parse_args()
