"""ResNet-50/ImageNet, multi-process/multi-host data parallel —
≙ ``restnet_ddp.py`` (R3; the reference repo's filename typo is theirs).

The reference forks 8 NUMA-bound processes per node, TCP-rendezvouses a
NCCL process group, and wraps the model in DistributedDataParallel
(``restnet_ddp.py:87-99,154-155``). Here: one process per host joins the
JAX coordination service (same MASTER_IP/MASTER_PORT/WORLD_SIZE/RANK env
contract), and the mesh spans every chip in the job — gradient all-reduce
compiles into the step and rides ICI/DCN.

Single host, no env vars → runs on the local chips (still the DDP recipe,
world of one).

    MASTER_IP=… MASTER_PORT=… WORLD_SIZE=<hosts> RANK=<host_idx> \
        python recipes/resnet_ddp.py          # on every host
"""

from common import parse_args, run  # noqa: E402  (bootstraps sys.path)

import pytorch_distributed_tpu as pdt

pdt.set_env("202607")

from pytorch_distributed_tpu.parallel import init_process_group, make_mesh  # noqa: E402


if __name__ == "__main__":
    args = parse_args(__doc__)
    init_process_group()  # ≙ dist.init_process_group('nccl', ...), restnet_ddp.py:94
    run(args, make_mesh(), precision="fp32")
