"""Paged-KV continuous-batching serving demo (round 6 tentpole).

Drives ``serving.Scheduler`` — the block-pooled KV cache with O(prompt)
admission, chunked prefill interleaved with decode, FIFO queueing on pool
OOM — over a synthetic multi-tenant workload, and prints the scheduler's
exact host-side metrics (occupancy, padding waste, admission latency,
queue depth, tokens/s). Zero required args; CPU-runnable:

    python recipes/serve_lm.py --tiny                 # CPU smoke
    python recipes/serve_lm.py --requests 64 --slots 16 --max-new 32
    python recipes/serve_lm.py --dense                # r4 layout A/B

``--dense`` runs the same workload through the legacy dense
``ContinuousBatcher`` layout (one max_seq_len KV row per slot, admission
copying the full row) for an on-box A/B of the admission tax the paged
engine removes; ANALYSIS.md "Serving engine" documents the design.

Telemetry (round 7; ANALYSIS.md "Observability & goodput"):
``--metrics-out serve.jsonl`` streams one ``kind="request"`` record per
retirement (queue wait, TTFT, inter-token gaps) plus a final
``kind="serving_summary"`` with the scheduler's percentile metrics —
feed it to ``scripts/telemetry_report.py`` for TTFT/per-token p50/p95;
``--trace-dir DIR`` writes the host span Chrome trace
(admission/prefill_chunk/decode_tick) to ``DIR/spans.trace.json``.

Elastic load (round 9; ANALYSIS.md "Elastic topology & reshard"):
``--restore CKPT`` serves a TRAINER checkpoint — sharded directory or
legacy single file, written on ANY mesh shape — with the params
re-partitioned from the serving rule table at ``--tp N``'s degree
(reading only the params blocks, never the optimizer moments):

    python recipes/serve_lm.py --tiny --restore out_lm/latest.ckpt --tp 2

Fleet (round 10; ANALYSIS.md "Serving fleet"): ``--replicas N`` serves
through ``fleet.FleetRouter`` — N single-process replica engines with
session-affinity routing and the SLO admission gate (``--slo-ttft-ms``
sets the TTFT target it spills/sheds against); ``--disaggregate`` splits
the replicas into prefill-only and decode roles with KV-block handoff
(``--prefill-replicas`` sizes the split); ``--trace T.jsonl`` replays a
seeded bursty heavy-tail traffic trace (``scripts/bench_serving.py
--gen-trace``) instead of the all-at-once synthetic workload:

    python scripts/bench_serving.py --gen-trace /tmp/t.jsonl --trace-duration 30
    python recipes/serve_lm.py --tiny --replicas 2 --trace /tmp/t.jsonl \
        --slo-ttft-ms 500 --metrics-out fleet.jsonl
    python recipes/serve_lm.py --tiny --replicas 2 --disaggregate

KV pressure (round 13; ANALYSIS.md "KV pressure & preemption"):
``--preempt`` turns memory pressure into preemptions instead of waits
or sheds — idle chains swap to a host-RAM block store (or recompute,
whichever the measured cost card says is cheaper) and restore before
their next tick; ``--n-blocks`` sizes the pool small to provoke it:

    python recipes/serve_lm.py --tiny --requests 24 --slots 4 \
        --n-blocks 12 --preempt --metrics-out pressure.jsonl

Request tracing (round 14; ANALYSIS.md "Request-lifecycle tracing"):
whenever ``--metrics-out`` is on, every request's lifecycle rides the
JSONL as a causal span tree (``kind="span"``: gate decision → queue →
prefill → handoff → decode windows → preempt/park/restore → retire).
``scripts/explain_request.py`` reconstructs any rid's story and
``--assert-complete`` gates on a closed acyclic tree; ``--swap-policy
swap`` forces the preemption path the trace smoke audits
(predicted-vs-measured swap wall in every preempt span):

    python recipes/serve_lm.py --tiny --replicas 2 --disaggregate \
        --preempt --swap-policy swap --metrics-out spans.jsonl
    python scripts/explain_request.py spans.jsonl --find preempted

Front door (round 22; ANALYSIS.md "Front door"): ``--http-port PORT``
(0 picks an ephemeral port, printed at startup) serves the fleet over
HTTP instead of replaying the synthetic workload — ``POST
/v1/generate`` streams tokens as Server-Sent Events with
``X-Deadline-Ms`` mapped onto the admission deadline, SLO sheds
surfacing as 429 + ``Retry-After``, and client disconnects cancelling
the request (KV blocks freed, span tree closed ``outcome=cancelled``);
``GET /v1/health`` is the round-19 health plane and ``/metrics`` the
Prometheus text. ``--http-duration`` bounds the serve window:

    python recipes/serve_lm.py --tiny --replicas 2 --http-port 8080 \
        --slo-ttft-ms 500 --metrics-out http.jsonl

Cold start (round 8; ANALYSIS.md "Cold start & compile cache"):
``--warmup`` compiles every registry program (decode tick + all prefill
buckets) before admitting traffic, and ``--compile-cache-dir`` points
jax's persistent compilation cache at a directory so a relaunched server
loads those programs from disk — ``scripts/warmup.py`` prewarms the
cache out-of-band and ``scripts/bench_coldstart.py`` proves the
compile-fraction collapse.
"""

from common import parse_args  # noqa: F401  (bootstraps sys.path)

import argparse
import json
import time

import numpy as np

import pytorch_distributed_tpu as pdt

pdt.set_env("202607")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from pytorch_distributed_tpu.models.generate import (  # noqa: E402
    ContinuousBatcher,
)
from pytorch_distributed_tpu.models.transformer import (  # noqa: E402
    TransformerConfig,
    TransformerLM,
    tiny_config,
)
from pytorch_distributed_tpu.serving import Scheduler  # noqa: E402
from pytorch_distributed_tpu.utils.logging import rank0_print  # noqa: E402


def _parse() -> argparse.Namespace:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--tiny", action="store_true",
                   help="tiny config (CPU smoke)")
    p.add_argument("--requests", type=int, default=24,
                   help="synthetic requests to serve")
    p.add_argument("--slots", type=int, default=8, help="decode lanes")
    p.add_argument("--max-new", type=int, default=16,
                   help="decode budget per request")
    p.add_argument("--block-len", type=int, default=16,
                   help="KV block length (paged layout)")
    p.add_argument("--n-blocks", type=int, default=None,
                   help="KV pool size in blocks (default: capacity "
                        "parity with the dense layout; set it SMALL to "
                        "over-commit the pool and exercise the round-13 "
                        "pressure tier)")
    # KV pressure tier (round 13; ANALYSIS.md "KV pressure & preemption")
    p.add_argument("--preempt", action="store_true",
                   help="enable the KV pressure tier: host-RAM offload "
                        "+ preempt-and-restore. Single scheduler: pool "
                        "OOM preempts the LRU resident chain instead of "
                        "making the queue wait for a retirement. Fleet: "
                        "the SLO gate's preempt rung turns would-be "
                        "sheds into cheap preemptions")
    p.add_argument("--swap-policy", choices=("auto", "swap", "recompute"),
                   default="auto",
                   help="preemption path: 'auto' takes the measured "
                        "swap-vs-recompute crossover per request; "
                        "'swap'/'recompute' force one side (the trace "
                        "smoke forces swap so the predicted-vs-measured "
                        "wall lands in every preempt span)")
    p.add_argument("--slo-shed-depth", type=int, default=None,
                   help="fleet shed queue depth (with --preempt the "
                        "gate preempts instead of shedding at this "
                        "bound; spill bound is set to a quarter of it)")
    p.add_argument("--prefill-chunk", type=int, default=32,
                   help="prefill chunk length (paged) / bucket (dense)")
    p.add_argument("--admit-per-step", type=int, default=4,
                   help="max admissions per scheduler tick")
    p.add_argument("--gather-impl", choices=("dense", "pallas"),
                   default=None,
                   help="paged KV gather spelling: 'dense' jnp.take or "
                        "'pallas' fused kernel (ops/paged_flash.py; "
                        "interpret mode off-TPU)")
    p.add_argument("--kv-dtype", choices=("int8", "fp8", "fp8_e5m2"),
                   default=None,
                   help="quantize the KV block pool: 'int8' (+fp32 "
                        "per-row scales, ~2D/(D+4) blocks at fixed pool "
                        "bytes) or 'fp8'/'fp8_e5m2' (e4m3/e5m2 + int8 "
                        "exponent scales, ~2D/(D+1))")
    p.add_argument("--split-s", type=int, default=None,
                   help="flash-decoding: split each chain sweep across "
                        "this many grid workers (log-sum-exp combine). "
                        "Default auto: splits when table-width/batch "
                        "crosses the ops.paged_flash threshold; 1 forces "
                        "the single-worker sweep")
    p.add_argument("--autotune-dir", default=None,
                   help="load an autotuned kernel config "
                        "(scripts/autotune.py output; env fallback "
                        "PDT_AUTOTUNE_DIR) keyed by this run's "
                        "fingerprint — a stale or missing file is a "
                        "clean miss, never an error")
    p.add_argument("--prefix-cache", action="store_true",
                   help="round-17 prefix-sharing KV cache: radix reuse "
                        "of full prompt blocks with copy-on-write — a "
                        "shared-system-prompt request admits in O(new "
                        "tokens); greedy streams stay token-identical")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--dense", action="store_true",
                   help="run the r4 dense layout instead (A/B reference)")
    p.add_argument("--metrics-out", default=None,
                   help="JSONL telemetry stream: per-request latency "
                        "records + a serving_summary (read with "
                        "scripts/telemetry_report.py)")
    p.add_argument("--trace-dir", default=None,
                   help="write the host span Chrome trace "
                        "(admission/prefill_chunk/decode_tick) to "
                        "<dir>/spans.trace.json")
    # Compile cache (compilecache/; ANALYSIS.md "Cold start & compile
    # cache"). Example — prewarm once, then every server start is warm:
    #   python scripts/warmup.py --tiny --compile-cache-dir /tmp/cc
    #   python recipes/serve_lm.py --tiny --warmup --compile-cache-dir /tmp/cc
    p.add_argument("--compile-cache-dir", default=None,
                   help="persistent XLA compilation cache directory (env "
                        "fallback PDT_COMPILE_CACHE_DIR): a relaunched "
                        "server loads its bucket programs from disk "
                        "instead of recompiling mid-traffic")
    p.add_argument("--warmup", action="store_true",
                   help="compile every registry program (decode tick + "
                        "all prefill buckets) before admitting traffic — "
                        "zero cold requests; paged layout only")
    # Elastic load (reshard/; ANALYSIS.md "Elastic topology & reshard"):
    # serve a TRAINER checkpoint at whatever TP degree this fleet runs —
    # the params are re-partitioned from the serving rule table, never
    # from the layout the trainer saved (a dp4xtp2 training checkpoint
    # serves on tp1 single-chip replicas or a tp4 latency mesh alike).
    p.add_argument("--restore", default=None, metavar="CKPT",
                   help="load model params from a trainer checkpoint "
                        "(sharded dir or legacy single file) instead of "
                        "random init — any writer topology")
    p.add_argument("--tp", type=int, default=1,
                   help="serving tensor-parallel degree (needs that many "
                        "devices; params are placed per the serving TP "
                        "rules at THIS degree, whatever degree wrote the "
                        "checkpoint)")
    # Fleet (fleet/; ANALYSIS.md "Serving fleet")
    p.add_argument("--replicas", type=int, default=1,
                   help="serve through a FleetRouter with this many "
                        "replicas (session-affinity routing + SLO "
                        "admission gate); 1 without --trace/--disaggregate "
                        "keeps the single-scheduler path")
    p.add_argument("--disaggregate", action="store_true",
                   help="split replicas into prefill-only and decode "
                        "roles with KV-block handoff (needs --replicas "
                        ">= 2)")
    p.add_argument("--async-host", action="store_true",
                   help="round-16 async host runtime: dispatch-then-"
                        "collect replica ticks (lagged token collect) "
                        "+ worker threads for JSONL/gate-metric host "
                        "work; greedy token streams identical to the "
                        "synchronous loop")
    p.add_argument("--prefill-replicas", type=int, default=1,
                   help="prefill replicas when --disaggregate")
    p.add_argument("--slo-ttft-ms", type=float, default=None,
                   help="TTFT p95 target for the admission gate: a "
                        "replica whose live p95 exceeds it is spilled "
                        "around; every replica past the shed queue "
                        "depth => explicit reject")
    p.add_argument("--trace", default=None, metavar="JSONL",
                   help="replay a traffic trace (bench_serving.py "
                        "--gen-trace) instead of submitting the "
                        "synthetic workload all at once")
    # Attribution & forensics (telemetry/; ANALYSIS.md "Performance
    # attribution & forensics")
    p.add_argument("--cost-cards", action="store_true",
                   help="after the serve cycle, emit one "
                        "kind=\"program_cost\" record per registry "
                        "program (compiler FLOPs/bytes joined with "
                        "measured tick wall → MFU/roofline; "
                        "telemetry_report.py renders the table). "
                        "AOT-compiles every not-yet-compiled bucket "
                        "once, after traffic; paged layout only")
    p.add_argument("--metrics-port", type=int, default=None,
                   help="serve live Prometheus-text /metrics while the "
                        "cycle runs (stdlib HTTP thread)")
    p.add_argument("--http-port", type=int, default=None, metavar="PORT",
                   help="serve the HTTP/SSE front door (gateway/) on "
                        "PORT (0 = ephemeral) instead of replaying the "
                        "synthetic workload: POST /v1/generate streams "
                        "tokens, GET /v1/health is the health plane, "
                        "/metrics the Prometheus text; implies the "
                        "fleet layout with the async host loop and "
                        "streaming retention")
    p.add_argument("--http-duration", type=float, default=10.0,
                   help="seconds to keep the front door up "
                        "(--http-port)")
    return p.parse_args()


def _model(args):
    tp = dict(model_axis="model", tp_size=args.tp) if args.tp > 1 else {}
    if args.tiny or jax.default_backend() == "cpu":
        cfg = tiny_config(attention="dense", max_seq_len=128, **tp)
    else:
        cfg = TransformerConfig(
            vocab_size=32_000, num_layers=12, num_heads=12, embed_dim=768,
            max_seq_len=2048, attention="dense", dropout=0.0, **tp,
        )
    mesh = None
    if args.tp > 1:
        from pytorch_distributed_tpu.parallel import make_mesh

        mesh = make_mesh(jax.devices()[: args.tp], data_parallel=1,
                         seq_parallel=1, model_parallel=args.tp)
    if args.restore:
        from pytorch_distributed_tpu.reshard import load_trainer_params

        params, info = load_trainer_params(args.restore, cfg, mesh=mesh)
        rank0_print(f"restore: {info.describe()}")
        return cfg, params, mesh
    params = TransformerLM(cfg).init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return cfg, params, mesh


def _prompts(args, cfg):
    rng = np.random.default_rng(args.seed)
    lens = rng.integers(4, cfg.max_seq_len - args.max_new,
                        size=args.requests)
    return [rng.integers(1, cfg.vocab_size, size=l).astype(np.int32)
            for l in lens]


# the live front-door instance when --http-port is up — an in-process
# driver (a test thread, a notebook) polls serve_lm.GATEWAY.port instead
# of scraping stdout for the ephemeral port
GATEWAY = None


def main() -> None:
    global GATEWAY
    args = _parse()
    from pytorch_distributed_tpu.utils.env import resolve_compile_cache_dir

    cache_dir = resolve_compile_cache_dir(args.compile_cache_dir)
    if cache_dir:
        from pytorch_distributed_tpu.compilecache import (
            enable_persistent_cache,
        )

        # before the model init below: its programs land in the cache too
        enable_persistent_cache(cache_dir)
    cfg, params, mesh = _model(args)
    prompts = _prompts(args, cfg)
    from pytorch_distributed_tpu.telemetry import (
        NULL_REQTRACER,
        NULL_TRACER,
        ReqTracer,
        SpanTracer,
    )
    from pytorch_distributed_tpu.utils.profiling import MetricsLogger

    tracer = SpanTracer() if args.trace_dir else NULL_TRACER
    mlog = MetricsLogger(args.metrics_out)
    # request-lifecycle tracing (round 14): whenever the JSONL stream is
    # on, every request's causal span tree rides along as kind="span"
    # records — scripts/explain_request.py reconstructs any rid from it
    reqtrace = (
        ReqTracer(mlog) if args.metrics_out and not args.dense
        else NULL_REQTRACER
    )
    t0 = time.perf_counter()
    http_mode = args.http_port is not None
    fleet_mode = (args.replicas > 1 or args.disaggregate or args.trace
                  or args.async_host or http_mode)
    if args.dense and (args.cost_cards or args.metrics_port is not None):
        raise SystemExit("--cost-cards/--metrics-port need the paged "
                         "layout (program registry + scheduler metrics); "
                         "drop --dense")
    exporter = None
    if fleet_mode and args.dense:
        raise SystemExit("--replicas/--disaggregate/--trace need the "
                         "paged layout; drop --dense")
    if fleet_mode and args.tp > 1:
        raise SystemExit("fleet replicas are single-device in this "
                         "round; drop --tp or --replicas")
    if fleet_mode:
        from pytorch_distributed_tpu.fleet import (
            FleetRouter,
            SLOConfig,
            clamp_trace,
            load_trace,
            prompt_for,
            replay_trace,
        )

        slo_kw = {}
        if args.slo_ttft_ms is not None:
            slo_kw["ttft_p95_ms"] = args.slo_ttft_ms
        if args.slo_shed_depth is not None:
            slo_kw["shed_queue_depth"] = args.slo_shed_depth
            slo_kw["spill_queue_depth"] = max(1, args.slo_shed_depth // 4)
        slo = SLOConfig(**slo_kw)
        pressure_kw = (
            dict(offload=True, preempt_on_oom=True,
                 swap_policy=args.swap_policy)
            if args.preempt else {}
        )
        router = FleetRouter(
            cfg, params, n_replicas=max(args.replicas, 2)
            if args.disaggregate else args.replicas,
            disaggregate=args.disaggregate,
            n_prefill=args.prefill_replicas, slo=slo, seed=args.seed,
            metrics_log=mlog, tracer=tracer, reqtrace=reqtrace,
            # the front door streams: async host loop, results dropped
            # at retire (the connection consumed them token by token)
            async_host=args.async_host or http_mode,
            retain_results=not http_mode,
            n_slots=args.slots,
            block_len=args.block_len, prefill_chunk=args.prefill_chunk,
            admit_per_step=args.admit_per_step, n_blocks=args.n_blocks,
            gather_impl=args.gather_impl, kv_dtype=args.kv_dtype,
            prefix_cache=args.prefix_cache, split_s=args.split_s,
            autotune_dir=args.autotune_dir,
            **pressure_kw,
        )
        if args.warmup:
            router.warmup()
        if args.metrics_port is not None:
            from pytorch_distributed_tpu.telemetry import MetricsExporter

            exporter = MetricsExporter(
                router.metrics, port=args.metrics_port
            ).start()
            rank0_print(f"metrics: http://127.0.0.1:{exporter.port}/metrics")
        if http_mode:
            from pytorch_distributed_tpu.gateway import Gateway

            GATEWAY = gw = Gateway(router, port=args.http_port,
                                   metrics_log=mlog)
            gw.start()
            rank0_print(
                f"gateway: http://127.0.0.1:{gw.port}/v1/generate "
                f"(health /v1/health, metrics /metrics; up for "
                f"{args.http_duration:.0f}s)")
            try:
                time.sleep(args.http_duration)
            finally:
                gw.stop()
                router.drain()
        elif args.trace:
            trace = clamp_trace(
                load_trace(args.trace), cfg.max_seq_len,
                args.prefill_chunk,
            )
            replay_trace(
                trace,
                lambda r: router.submit(prompt_for(r, cfg.vocab_size),
                                        r.max_new, session=r.session),
                router.step,
                lambda: router.idle,
            )
            # the fleet is idle here, so this runs only the drain
            # epilogue: the host-work flush barrier and (under
            # PDT_BLOCKSAN=1) the fleet-wide ledger quiesce check
            router.drain()
        else:
            for i, p in enumerate(prompts):
                router.submit(p, args.max_new, session=i % 8)
            router.drain()
        metrics = {"layout": "fleet", **router.metrics()}
        router.log_summary()
        if args.cost_cards:
            for rep in router.replicas:
                rep.log_cost_cards()
        if exporter is not None:
            exporter.stop()
        metrics["wall_s"] = round(time.perf_counter() - t0, 2)
        mlog.close()
        if args.trace_dir:
            import os

            tracer.save(os.path.join(args.trace_dir, "spans.trace.json"))
        rank0_print(json.dumps(metrics, indent=2))
        return
    if args.dense:
        if args.warmup:
            raise SystemExit("--warmup needs the paged layout (the dense "
                             "ContinuousBatcher has no program registry); "
                             "drop --dense")
        if (args.gather_impl or args.kv_dtype or args.prefix_cache
                or args.split_s is not None or args.autotune_dir):
            raise SystemExit("--gather-impl/--kv-dtype/--prefix-cache/"
                             "--split-s/--autotune-dir are block-pool "
                             "knobs; drop --dense")
        if args.preempt or args.n_blocks is not None:
            raise SystemExit("--preempt/--n-blocks are block-pool knobs "
                             "(the pressure tier swaps BLOCKS); drop "
                             "--dense")
        if args.tp > 1:
            raise SystemExit("--tp > 1 needs the paged layout; drop "
                             "--dense")
        # r4 layout: no queue — submit when a slot frees, the admission
        # itself copying the slot's full max_seq_len KV row
        b = ContinuousBatcher(
            cfg, params, n_slots=args.slots, seed=args.seed,
            prefill_bucket=args.prefill_chunk, cache_layout="dense",
        )
        waiting = list(prompts)
        done = 0
        while waiting or any(b.remaining > 0):
            while waiting and b.free_slots():
                b.submit(waiting.pop(0), args.max_new)
            done += len(b.step())
        metrics = {"layout": "dense", "tokens_out": done}
    else:
        s = Scheduler(
            cfg, params, n_slots=args.slots, block_len=args.block_len,
            prefill_chunk=args.prefill_chunk, n_blocks=args.n_blocks,
            admit_per_step=args.admit_per_step, seed=args.seed,
            mesh=mesh, tracer=tracer, metrics_log=mlog,
            reqtrace=reqtrace,
            gather_impl=args.gather_impl, kv_dtype=args.kv_dtype,
            offload=args.preempt, preempt_on_oom=args.preempt,
            swap_policy=args.swap_policy,
            prefix_cache=args.prefix_cache, split_s=args.split_s,
            autotune_dir=args.autotune_dir,
        )
        if args.warmup:
            # everything foreground + executed inert: the serve loop below
            # admits immediately after, so every request must be warm
            runner = s.warmup(background=False)
            ws = runner.summary()
            rank0_print(
                f"warmup: {ws['programs']} programs in "
                f"{ws['total_s']:.2f}s ({ws['cache_hits']} cache hits)"
            )
        if args.metrics_port is not None:
            from pytorch_distributed_tpu.telemetry import MetricsExporter

            exporter = MetricsExporter(
                s.metrics, port=args.metrics_port
            ).start()
            rank0_print(f"metrics: http://127.0.0.1:{exporter.port}/metrics")
        for p in prompts:
            s.submit(p, args.max_new)
        streams = s.drain()
        metrics = {"layout": "paged", **s.metrics()}
        if args.cost_cards:
            s.log_cost_cards()
        if exporter is not None:
            exporter.stop()
        assert len(streams) == args.requests
    metrics["wall_s"] = round(time.perf_counter() - t0, 2)
    mlog.log(kind="serving_summary", **metrics)
    mlog.close()
    if args.trace_dir:
        import os

        tracer.save(os.path.join(args.trace_dir, "spans.trace.json"))
    rank0_print(json.dumps(metrics, indent=2))


if __name__ == "__main__":
    main()
