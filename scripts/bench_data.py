"""Input-pipeline throughput benchmark (VERDICT r1 missing #2).

The reference's entire data story is ffrecord sustaining ~5,500 img/s
(``/root/reference/README.md:13-18``). This measures every stage of this
framework's pipeline on the actual host:

  1. raw record read      — TPRC C++ reader, MB/s and rec/s
  2. JPEG decode+augment  — ImageNet dataset (PIL) through the DataLoader
  3. raw fast path        — RawImageNet (no decode), "rrc" and "crop" augs
  4. end-to-end           — loader → shard_batch (H2D) when a TPU is visible

Prints one JSON line per stage plus a per-core scaling verdict: the chip
needs ~2,700 img/s (bench.py headline); stages are measured with
``num_workers = os.cpu_count()`` threads so the img/s ÷ cores number says
how many host cores one chip's feed costs.

Usage: python scripts/bench_data.py [--n 2048] [--skip-jpeg]
"""

from __future__ import annotations

import io
import json
import os
import sys
import tempfile
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def synth_jpegs(n: int, size: int = 256):
    from PIL import Image

    rng = np.random.default_rng(0)
    for i in range(n):
        # structured noise compresses like a photo, not like white noise
        base = rng.integers(0, 255, (size // 8, size // 8, 3), np.uint8)
        arr = np.kron(base, np.ones((8, 8, 1), np.uint8))
        buf = io.BytesIO()
        Image.fromarray(arr).save(buf, "JPEG", quality=90)
        yield buf.getvalue(), i % 1000


def build_splits(tmp: str, n: int):
    from pytorch_distributed_tpu.data.imagenet import write_imagenet_split
    from pytorch_distributed_tpu.data.raw import write_imagenet_raw_split

    jpeg_path = os.path.join(tmp, "train.tprc")
    raw_path = os.path.join(tmp, "train.rawtprc")
    t0 = time.perf_counter()
    write_imagenet_split(jpeg_path, synth_jpegs(n))
    t1 = time.perf_counter()
    write_imagenet_raw_split(raw_path, synth_jpegs(n))
    t2 = time.perf_counter()
    print(json.dumps({"stage": "pack", "n": n,
                      "jpeg_pack_s": round(t1 - t0, 2),
                      "raw_pack_s": round(t2 - t1, 2),
                      "raw_mb": round(os.path.getsize(raw_path) / 2**20, 1)}))
    return jpeg_path, raw_path


def bench_reader(path: str, n: int):
    from pytorch_distributed_tpu.data.packed_record import PackedRecordReader

    r = PackedRecordReader(path)
    idx = np.random.default_rng(1).permutation(len(r))[:n]
    for verify in (True, False):
        t0 = time.perf_counter()
        total = 0
        for lo in range(0, len(idx), 256):
            for rec in r.read_batch(
                [int(i) for i in idx[lo : lo + 256]], verify_crc=verify
            ):
                total += len(rec)
        dt = time.perf_counter() - t0
        print(json.dumps({"stage": "record_read", "verify_crc": verify,
                          "native": r._native is not None,
                          "rec_s": round(len(idx) / dt, 1),
                          "mb_s": round(total / 2**20 / dt, 1)}))


def bench_loader(name: str, dataset, n: int, workers: int):
    from pytorch_distributed_tpu.data.loader import DataLoader, measure_throughput

    loader = DataLoader(dataset, batch_size=128, num_workers=workers,
                        drop_last=True, prefetch=4)
    first = next(iter(loader))  # dtype for the record (separate iterator)
    img_s = measure_throughput(loader)  # fresh epoch: unbiased, no pre-fill
    cores = os.cpu_count() or 1
    print(json.dumps({"stage": name, "img_s": round(img_s, 1),
                      "workers": workers, "dtype": str(first["image"].dtype),
                      "img_s_per_core": round(img_s / cores, 1)}))
    return img_s


def bench_end_to_end(dataset, n: int, workers: int):
    import jax

    from pytorch_distributed_tpu.data.loader import DataLoader
    from pytorch_distributed_tpu.parallel import shard_batch, single_device_mesh

    mesh = single_device_mesh()
    loader = DataLoader(dataset, batch_size=128, num_workers=workers,
                        drop_last=True, prefetch=4)
    it = loader.iter_batches(0)
    dev = shard_batch(mesh, next(it))
    t0 = time.perf_counter()
    seen = 0
    for batch in it:
        dev = shard_batch(mesh, batch)  # async H2D
        seen += batch["image"].shape[0]
        if seen >= n:
            break
    np.asarray(jax.device_get(dev["label"]))[:1]  # drain transfers
    dt = time.perf_counter() - t0
    print(json.dumps({"stage": "end_to_end_h2d", "img_s": round(seen / dt, 1),
                      "platform": jax.devices()[0].platform}))


def main() -> None:
    n = 2048
    if "--n" in sys.argv:
        n = int(sys.argv[sys.argv.index("--n") + 1])
    workers = os.cpu_count() or 1
    with tempfile.TemporaryDirectory() as tmp:
        jpeg_path, raw_path = build_splits(tmp, n)
        bench_reader(raw_path, n)

        from pytorch_distributed_tpu.data.imagenet import ImageNet
        from pytorch_distributed_tpu.data.raw import RawImageNet

        if "--skip-jpeg" not in sys.argv:
            bench_loader("jpeg_decode_rrc", ImageNet("train", data_dir=tmp),
                         n, workers)
        bench_loader("raw_rrc", RawImageNet("train", data_dir=tmp, aug="rrc"),
                     n, workers)
        bench_loader("raw_crop_py",
                     RawImageNet("train", data_dir=tmp, aug="crop",
                                 use_native=False),
                     n, workers)
        # native whole-batch C path (tpr_crop_batch): read+crop+flip+collate
        # in one GIL-free threaded call
        bench_loader("raw_crop_native",
                     RawImageNet("train", data_dir=tmp, aug="crop"),
                     n, workers)
        try:
            bench_end_to_end(RawImageNet("train", data_dir=tmp, aug="crop"),
                             n, workers)
        except Exception as e:  # no device/backend — host stages still stand
            print(json.dumps({"stage": "end_to_end_h2d", "error": str(e)[:120]}))


if __name__ == "__main__":
    main()
