"""Pipeline-parallel bubble measurement (VERDICT r2 next #8).

GPipe's schedule runs M + S - 1 ticks for M microbatches over S stages;
the warm-up/drain ticks compute masked garbage, so the overhead over a
bubble-free schedule is (M + S - 1)/M — equivalently a bubble fraction
(S - 1)/(M + S - 1) of all ticks. On the 8-virtual-device CPU mesh the
stages serialize onto one core, which makes the bubble DIRECTLY visible
in wall-clock (garbage ticks burn real FLOPs), so step time vs M measures
the schedule itself, not ICI behavior. This script sweeps M at fixed
local batch, fits measured step time against the tick model, and reports
the smallest M within 5% of the large-M asymptote — the data behind the
``n_microbatches`` default.

Run: XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
     python scripts/bench_pp.py
Emits one JSON line per M plus a summary line.
"""

from __future__ import annotations

import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P


def main() -> None:
    from pytorch_distributed_tpu.models.transformer import tiny_config
    from pytorch_distributed_tpu.ops.optim import sgd_with_weight_decay
    from pytorch_distributed_tpu.parallel import make_mesh
    from pytorch_distributed_tpu.train.lm import shift_labels
    from pytorch_distributed_tpu.train.pp import (
        create_pp_lm_state,
        make_pp_lm_train_step,
        shard_pp_state,
    )

    stages, local_b, seq = 4, 16, 64
    mesh = make_mesh(jax.devices()[:8], data_parallel=2, model_parallel=stages)
    cfg = tiny_config(num_layers=stages, max_seq_len=seq)
    tx = sgd_with_weight_decay(0.1, momentum=0.9)
    sh = NamedSharding(mesh, P("data"))
    rng = np.random.default_rng(0)
    tokens = rng.integers(1, 128, (2 * local_b, seq)).astype(np.int32)
    labels, weights = shift_labels(tokens)
    batch = {
        "tokens": jax.device_put(tokens, sh),
        "labels": jax.device_put(labels, sh),
        "weights": jax.device_put(weights, sh),
    }

    rows = []
    for m in (1, 2, 4, 8, 16):
        state = create_pp_lm_state(cfg, stages, tx, jax.random.key(0),
                                   init_len=seq)
        state, specs = shard_pp_state(mesh, state)
        step = make_pp_lm_train_step(mesh, cfg, specs, n_microbatches=m)
        state, metrics = step(state, batch)  # compile + warm
        float(metrics["loss"])
        t0 = time.perf_counter()
        iters = 8
        for _ in range(iters):
            state, metrics = step(state, batch)
        float(metrics["loss"])
        dt = (time.perf_counter() - t0) / iters
        bubble = (stages - 1) / (m + stages - 1)
        rows.append((m, dt, bubble))
        print(json.dumps({
            "pp_microbatches": m,
            "step_ms": round(dt * 1e3, 1),
            "ticks": m + stages - 1,
            "bubble_frac_model": round(bubble, 3),
            "overhead_model": round((m + stages - 1) / m, 3),
        }), flush=True)

    # pick: smallest M whose step time is within 5% of the best measured
    best = min(dt for _, dt, _ in rows)
    pick = next(m for m, dt, _ in rows if dt <= 1.05 * best)
    print(json.dumps({
        "pp_summary": {
            "stages": stages,
            "best_step_ms": round(best * 1e3, 1),
            "recommended_microbatches": pick,
            "note": "per-tick overhead grows past the bubble win at large "
                    "M with tiny microbatches; see ROUND3 notes",
        }
    }))


if __name__ == "__main__":
    main()
