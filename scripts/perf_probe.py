"""Perf bisection probe for the ResNet-50 train step on the real chip.

Round-2 investigation of VERDICT.md Weak #1 (16% MFU, throughput flat with
batch size). Times each sub-computation of the step independently so the
cost can be attributed: pure matmul ceiling, forward, forward+backward,
full step, step-without-metrics. Run on the TPU (not under tests/conftest).

Usage: python scripts/perf_probe.py [probe ...]
Probes: matmul fwd fwdbwd full nometrics sweep
"""

from __future__ import annotations

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

RESNET50_FWD_GFLOP = 4.1  # per 224x224 image, standard count
RESNET50_STEP_GFLOP = 12.3  # fwd + bwd ~= 3x fwd


def timeit(fn, *args, iters=20, warmup=5):
    """Free-running chain timing with one final value fetch (cannot lie)."""
    out = None
    for _ in range(warmup):
        out = fn(*args)
    np.asarray(jax.device_get(jax.tree.leaves(out)[0])).ravel()[:1]
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    np.asarray(jax.device_get(jax.tree.leaves(out)[0])).ravel()[:1]
    return (time.perf_counter() - t0) / iters


def probe_matmul():
    """Achievable bf16 matmul TFLOP/s through the tunnel — the MXU ceiling."""
    for n in (4096, 8192):
        a = jnp.ones((n, n), jnp.bfloat16)
        b = jnp.ones((n, n), jnp.bfloat16)

        @jax.jit
        def mm(a, b):
            return jax.lax.dot(a, b, preferred_element_type=jnp.float32).astype(
                jnp.bfloat16
            )

        dt = timeit(mm, a, b)
        tflops = 2 * n**3 / dt / 1e12
        print(f"matmul {n}x{n}x{n} bf16: {dt * 1e3:.2f} ms  {tflops:.1f} TFLOP/s")


def build_state(batch_size, dtype=jnp.bfloat16):
    from pytorch_distributed_tpu.models import resnet50
    from pytorch_distributed_tpu.ops.optim import sgd_with_weight_decay
    from pytorch_distributed_tpu.parallel import (
        replicated_sharding,
        shard_batch,
        single_device_mesh,
    )
    from pytorch_distributed_tpu.train.state import TrainState

    model = resnet50(dtype=dtype)
    mesh = single_device_mesh()
    tx = sgd_with_weight_decay(0.1, momentum=0.9, weight_decay=1e-4)
    state = TrainState.create(model, tx, jax.random.key(0), (1, 224, 224, 3))
    state = jax.device_put(state, replicated_sharding(mesh))
    rng = np.random.default_rng(0)
    batch = shard_batch(
        mesh,
        {
            "image": rng.normal(size=(batch_size, 224, 224, 3)).astype(np.float32),
            "label": rng.integers(0, 1000, batch_size).astype(np.int32),
        },
    )
    return mesh, state, batch


def report(name, bs, dt, gflop_per_img, peak=197.0):
    tflops = bs * gflop_per_img * 1e9 / dt / 1e12
    print(
        f"{name:12s} bs={bs:4d}: {dt * 1e3:7.2f} ms  {bs / dt:7.0f} img/s  "
        f"{tflops:6.1f} TFLOP/s  ({100 * tflops / peak:.0f}% of {peak:.0f})"
    )


def probe_fwd(bs):
    mesh, state, batch = build_state(bs)

    @jax.jit
    def fwd(state, batch):
        variables = {"params": state.params, "batch_stats": state.batch_stats}
        out, _ = state.apply_fn(
            variables, batch["image"], train=True, mutable=["batch_stats"]
        )
        return out

    dt = timeit(fwd, state, batch)
    report("fwd", bs, dt, RESNET50_FWD_GFLOP)


def probe_fwdbwd(bs):
    from pytorch_distributed_tpu.ops.losses import cross_entropy_loss

    mesh, state, batch = build_state(bs)

    @jax.jit
    def fwdbwd(state, batch):
        def loss_fn(params):
            variables = {"params": params, "batch_stats": state.batch_stats}
            out, mut = state.apply_fn(
                variables, batch["image"], train=True, mutable=["batch_stats"]
            )
            return cross_entropy_loss(out, batch["label"]), mut

        grads, _ = jax.grad(loss_fn, has_aux=True)(state.params)
        return grads

    dt = timeit(fwdbwd, state, batch)
    report("fwd+bwd", bs, dt, RESNET50_STEP_GFLOP)


def probe_full(bs):
    from pytorch_distributed_tpu.train.step import make_train_step

    mesh, state, batch = build_state(bs)
    step = make_train_step(mesh)

    def run(state, batch):
        return step(state, batch)

    # donation: chain state through
    for _ in range(5):
        state, m = step(state, batch)
    float(m["loss"])
    t0 = time.perf_counter()
    iters = 20
    for _ in range(iters):
        state, m = step(state, batch)
    float(m["loss"])
    dt = (time.perf_counter() - t0) / iters
    report("full step", bs, dt, RESNET50_STEP_GFLOP)


def probe_nometrics(bs):
    from pytorch_distributed_tpu.ops.losses import cross_entropy_loss

    mesh, state, batch = build_state(bs)

    @jax.jit
    def step(state, batch):
        def loss_fn(params):
            variables = {"params": params, "batch_stats": state.batch_stats}
            out, mut = state.apply_fn(
                variables, batch["image"], train=True, mutable=["batch_stats"]
            )
            return cross_entropy_loss(out, batch["label"]), mut

        grads, mut = jax.grad(loss_fn, has_aux=True)(state.params)
        updates, opt_state = state.tx.update(grads, state.opt_state, state.params)
        params = jax.tree.map(jnp.add, state.params, updates)
        return state.replace(
            params=params,
            opt_state=opt_state,
            batch_stats=mut["batch_stats"],
            step=state.step + 1,
        )

    state2 = step(state, batch)
    for _ in range(4):
        state2 = step(state2, batch)
    np.asarray(jax.device_get(jax.tree.leaves(state2.params)[0])).ravel()[:1]
    t0 = time.perf_counter()
    iters = 20
    for _ in range(iters):
        state2 = step(state2, batch)
    np.asarray(jax.device_get(jax.tree.leaves(state2.params)[0])).ravel()[:1]
    dt = (time.perf_counter() - t0) / iters
    report("no-metrics", bs, dt, RESNET50_STEP_GFLOP)


def main():
    probes = sys.argv[1:] or ["matmul", "fwd", "fwdbwd", "nometrics", "full"]
    print(f"device: {jax.devices()[0]}")
    for p in probes:
        if p == "matmul":
            probe_matmul()
        elif p == "fwd":
            for bs in (128, 256):
                probe_fwd(bs)
        elif p == "fwdbwd":
            for bs in (128, 256):
                probe_fwdbwd(bs)
        elif p == "full":
            for bs in (128, 256):
                probe_full(bs)
        elif p == "nometrics":
            for bs in (128, 256):
                probe_nometrics(bs)
        elif p == "sweep":
            for bs in (64, 128, 256, 512, 1024):
                probe_full(bs)


if __name__ == "__main__":
    main()
