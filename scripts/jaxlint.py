#!/usr/bin/env python
"""jaxlint CLI — static SPMD/jit correctness lint over a source tree.

    python scripts/jaxlint.py pytorch_distributed_tpu/
    python scripts/jaxlint.py --list-rules
    python scripts/jaxlint.py --no-baseline tests/fixtures/jaxlint/

Exit codes: 0 no new findings; 1 new findings; 2 usage/internal error.

Pre-existing, reviewed findings live in scripts/jaxlint_baseline.json
(each with a reason) and don't fail the run; anything NOT in the baseline
does. The partition-coverage check needs an importable jax and is skipped
with a notice when that fails (e.g. a docs-only CI container).

Rules, severities and the suppression syntax are documented in ANALYSIS.md.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from pytorch_distributed_tpu.analysis import (  # noqa: E402
    all_rule_ids,
    load_baseline,
    run_lint,
    split_baselined,
)

DEFAULT_BASELINE = os.path.join(REPO, "scripts", "jaxlint_baseline.json")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="jaxlint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("paths", nargs="*", help="files or directories to lint")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline JSON of reviewed findings")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, baseline ignored")
    ap.add_argument("--no-partition-coverage", action="store_true",
                    help="skip the runtime partition-rule coverage check")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule, severity, desc in all_rule_ids():
            print(f"{rule:32} {severity:8} {desc}")
        return 0
    if not args.paths:
        ap.print_usage(sys.stderr)
        print("jaxlint: error: no paths given", file=sys.stderr)
        return 2

    findings = run_lint(args.paths, rel_root=REPO)

    lint_package = any(
        os.path.abspath(p).startswith(
            os.path.join(REPO, "pytorch_distributed_tpu")
        )
        for p in args.paths
    )
    if lint_package and not args.no_partition_coverage:
        try:
            os.environ.setdefault("JAX_PLATFORMS", "cpu")
            from pytorch_distributed_tpu.analysis.partition_coverage import (
                check_partition_coverage,
            )

            findings = list(findings) + check_partition_coverage()
        except ImportError as e:
            print(f"jaxlint: partition-coverage skipped (no jax: {e})",
                  file=sys.stderr)

    entries = []
    if not args.no_baseline and os.path.exists(args.baseline):
        entries = load_baseline(args.baseline)
    sources = {}
    for p in {f.path for f in findings}:
        ap_path = os.path.join(REPO, p)
        if os.path.exists(ap_path):
            with open(ap_path, "r", encoding="utf-8") as fh:
                sources[p] = fh.read().splitlines()
    new, baselined = split_baselined(findings, entries, sources)

    if args.format == "json":
        print(json.dumps({
            "new": [vars(f) for f in new],
            "baselined": [vars(f) for f in baselined],
        }, indent=2))
    else:
        for f in new:
            print(f.render())
        n_err = sum(1 for f in new if f.severity == "error")
        n_warn = len(new) - n_err
        print(
            f"jaxlint: {n_err} error(s), {n_warn} warning(s), "
            f"{len(baselined)} baselined finding(s)"
            + ("" if args.no_baseline or not os.path.exists(args.baseline)
               else f" [{os.path.relpath(args.baseline, REPO)}]")
        )
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
