#!/usr/bin/env python
"""jaxlint CLI — static SPMD/jit correctness lint over a source tree.

    python scripts/jaxlint.py pytorch_distributed_tpu/
    python scripts/jaxlint.py --list-rules
    python scripts/jaxlint.py --explain donation-use-after-donate
    python scripts/jaxlint.py --incremental pytorch_distributed_tpu/
    python scripts/jaxlint.py --changed pytorch_distributed_tpu/ scripts/
    python scripts/jaxlint.py --sarif-out output/jaxlint.sarif pytorch_distributed_tpu/
    python scripts/jaxlint.py --fix-baseline pytorch_distributed_tpu/
    python scripts/jaxlint.py --no-baseline tests/fixtures/jaxlint/

Exit codes: 0 no new findings; 1 new findings; 2 usage/internal error;
3 the --max-seconds budget was exceeded (findings notwithstanding).

Pre-existing, reviewed findings live in scripts/jaxlint_baseline.json
(each with a reason) and don't fail the run; anything NOT in the baseline
does. --fix-baseline regenerates that file from the current findings in
deterministic order, preserving reasons and dropping fixed entries — the
baseline only ever shrinks. --incremental serves unchanged files from a
content-hash cache (cross-module rules still re-run on any change). The
partition-coverage check needs an importable jax and is skipped with a
notice when that fails (e.g. a docs-only CI container). --changed
narrows the given paths to the .py files that differ from
``git merge-base HEAD main`` (tracked edits plus untracked files) — the
fast pre-push mode; it falls back to a full lint with a notice when git
or the main branch is unavailable, and exits 0 when nothing changed.

Rules and the suppression syntax are documented in ANALYSIS.md; the
long-form text behind --explain lives next to each rule's implementation
(``RuleInfo``), so the two cannot drift.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from pytorch_distributed_tpu.analysis import (  # noqa: E402
    all_rule_ids,
    explain_rule,
    load_baseline,
    regenerate_baseline,
    run_lint,
    run_lint_incremental,
    split_baselined,
    with_fingerprints,
    write_sarif,
)

DEFAULT_BASELINE = os.path.join(REPO, "scripts", "jaxlint_baseline.json")
DEFAULT_CACHE = os.path.join(REPO, ".jaxlint_cache.json")


def _changed_files(paths):
    """Resolve --changed: absolute .py paths under *paths* that differ
    from ``git merge-base HEAD main`` (tracked diffs plus untracked
    files). Returns ``(files, error)`` — on any git failure ``files`` is
    None and ``error`` says why, so the caller can fall back to a full
    lint rather than silently passing an unlinted tree."""
    import subprocess

    def _git(*cmd):
        res = subprocess.run(
            ["git", *cmd], capture_output=True, text=True, cwd=REPO,
            timeout=30,
        )
        if res.returncode != 0:
            raise RuntimeError(
                f"git {' '.join(cmd)}: {res.stderr.strip() or 'failed'}"
            )
        return res.stdout

    try:
        base = _git("merge-base", "HEAD", "main").strip()
        rels = _git("diff", "--name-only", base).splitlines()
        rels += _git(
            "ls-files", "--others", "--exclude-standard"
        ).splitlines()
    except (OSError, RuntimeError, subprocess.SubprocessError) as e:
        return None, str(e)
    roots = [os.path.abspath(p) for p in paths]
    files = []
    for rel in dict.fromkeys(rels):  # dedupe, keep order
        if not rel.endswith(".py"):
            continue
        abspath = os.path.join(REPO, rel)
        if not os.path.exists(abspath):
            continue  # deleted since the merge base
        if any(abspath == r or abspath.startswith(r + os.sep)
               for r in roots):
            files.append(abspath)
    return files, None


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="jaxlint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("paths", nargs="*", help="files or directories to lint")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline JSON of reviewed findings")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, baseline ignored")
    ap.add_argument("--fix-baseline", action="store_true",
                    help="regenerate the baseline from current findings "
                         "(deterministic order, reasons preserved) and exit 0")
    ap.add_argument("--no-partition-coverage", action="store_true",
                    help="skip the runtime partition-rule coverage check")
    ap.add_argument("--incremental", action="store_true",
                    help="serve unchanged files from the content-hash cache")
    ap.add_argument("--changed", action="store_true",
                    help="lint only .py files under the given paths that "
                         "differ from `git merge-base HEAD main` (plus "
                         "untracked files); exits 0 when nothing changed")
    ap.add_argument("--cache", default=DEFAULT_CACHE,
                    help="incremental cache file (gitignored)")
    ap.add_argument("--format", choices=("text", "json", "sarif"),
                    default="text")
    ap.add_argument("--sarif-out", metavar="FILE",
                    help="also write a SARIF 2.1.0 artifact to FILE")
    ap.add_argument("--max-seconds", type=float, default=None,
                    help="fail (exit 3) when the lint wall time exceeds "
                         "this budget — the CI timing gate")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--explain", metavar="RULE_ID",
                    help="print one rule's long-form documentation")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule, severity, desc in all_rule_ids():
            print(f"{rule:32} {severity:8} {desc}")
        return 0
    if args.explain:
        text = explain_rule(args.explain)
        if text is None:
            known = ", ".join(r for r, _s, _d in all_rule_ids())
            print(f"jaxlint: unknown rule {args.explain!r} — known rules: "
                  f"{known}", file=sys.stderr)
            return 2
        print(text)
        return 0
    if not args.paths:
        ap.print_usage(sys.stderr)
        print("jaxlint: error: no paths given", file=sys.stderr)
        return 2

    if args.changed:
        files, err = _changed_files(args.paths)
        if files is None:
            print(f"jaxlint: --changed unavailable ({err}) — "
                  f"falling back to a full lint", file=sys.stderr)
        elif not files:
            print("jaxlint: --changed — no .py files differ from "
                  "merge-base with main; nothing to lint")
            return 0
        else:
            print(f"jaxlint: --changed — {len(files)} file(s) differ "
                  f"from merge-base with main", file=sys.stderr)
            args.paths = files

    t0 = time.perf_counter()

    if args.incremental:
        inc = run_lint_incremental(args.paths, args.cache, rel_root=REPO)
        findings = inc.findings
        print(
            f"jaxlint: incremental — {inc.linted} file(s) linted, "
            f"{inc.cached} served from cache"
            + (" (context changed: full pass)" if inc.full_run else ""),
            file=sys.stderr,
        )
    else:
        findings = run_lint(args.paths, rel_root=REPO)

    lint_package = any(
        os.path.abspath(p).startswith(
            os.path.join(REPO, "pytorch_distributed_tpu")
        )
        for p in args.paths
    )
    if lint_package and not args.no_partition_coverage:
        try:
            os.environ.setdefault("JAX_PLATFORMS", "cpu")
            from pytorch_distributed_tpu.analysis.partition_coverage import (
                check_partition_coverage,
            )

            findings = list(findings) + check_partition_coverage()
        except ImportError as e:
            print(f"jaxlint: partition-coverage skipped (no jax: {e})",
                  file=sys.stderr)

    sources = {}
    for p in {f.path for f in findings}:
        ap_path = os.path.join(REPO, p)
        if os.path.exists(ap_path):
            with open(ap_path, "r", encoding="utf-8") as fh:
                sources[p] = fh.read().splitlines()
    # runtime-rule findings (partition coverage) arrive unfingerprinted
    findings = with_fingerprints(findings, sources)

    entries = []
    if not args.no_baseline and os.path.exists(args.baseline):
        entries = load_baseline(args.baseline)

    if args.fix_baseline:
        doc = regenerate_baseline(findings, entries, sources)
        with open(args.baseline, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=2)
            f.write("\n")
        n = len(doc["findings"])
        unreviewed = sum(
            1 for e in doc["findings"] if e["reason"].startswith("UNREVIEWED")
        )
        print(
            f"jaxlint: baseline regenerated — {n} entr"
            f"{'y' if n == 1 else 'ies'} ({len(entries)} before, "
            f"{unreviewed} UNREVIEWED need a reason or a fix): "
            f"{os.path.relpath(args.baseline, REPO)}"
        )
        return 0

    new, baselined = split_baselined(findings, entries, sources)

    if args.sarif_out:
        os.makedirs(os.path.dirname(args.sarif_out) or ".", exist_ok=True)
        write_sarif(args.sarif_out, new, baselined)
        print(f"jaxlint: SARIF written to {args.sarif_out}", file=sys.stderr)

    if args.format == "sarif":
        from pytorch_distributed_tpu.analysis import to_sarif

        print(json.dumps(to_sarif(new, baselined), indent=2))
    elif args.format == "json":
        print(json.dumps({
            "new": [vars(f) for f in new],
            "baselined": [vars(f) for f in baselined],
        }, indent=2))
    else:
        for f in new:
            print(f.render())
        n_err = sum(1 for f in new if f.severity == "error")
        n_warn = len(new) - n_err
        print(
            f"jaxlint: {n_err} error(s), {n_warn} warning(s), "
            f"{len(baselined)} baselined finding(s)"
            + ("" if args.no_baseline or not os.path.exists(args.baseline)
               else f" [{os.path.relpath(args.baseline, REPO)}]")
        )

    elapsed = time.perf_counter() - t0
    if args.max_seconds is not None and elapsed > args.max_seconds:
        print(
            f"jaxlint: wall time {elapsed:.1f}s exceeded the "
            f"--max-seconds {args.max_seconds:.1f}s budget",
            file=sys.stderr,
        )
        return 3
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
