"""Telemetry overhead micro-bench (ISSUE 4 acceptance: post-warmup step
time with the device metrics ring within noise — ≤2% — of telemetry
off, while the legacy blocking float() path shows the sync tax).

Three modes over the SAME compiled tiny-LM train step, post-warmup,
logging at the trainers' cadence (``--log-every``, default 100 — the
TrainerConfig default):

- ``off``       step only (the floor);
- ``ring``      step + a DeviceMetricsRing push at each log interval
                with lagged window drains (the new trainer path);
- ``blocking``  step + the seed path's ``float(metrics["loss"])`` at
                each log interval — the host sync this PR removes.

Reports mean post-warmup step ms per mode and the ring-vs-off delta
(the ≤2% acceptance gate). CPU-runnable; on device backends the
blocking tax grows with the dispatch round-trip (~95 ms through a
tunneled runtime, PERF_NOTES.md) while the ring cost stays one tiny
async dispatch per log event.

Usage: python scripts/bench_telemetry.py [--steps 600] [--log-every 100]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

import jax  # noqa: E402
import numpy as np  # noqa: E402


def _build():
    from pytorch_distributed_tpu.models.transformer import tiny_config
    from pytorch_distributed_tpu.ops.optim import build_optimizer
    from pytorch_distributed_tpu.ops.schedules import warmup_cosine
    from pytorch_distributed_tpu.parallel import make_mesh
    from pytorch_distributed_tpu.parallel import mesh as mesh_lib
    from pytorch_distributed_tpu.train.lm import (
        create_lm_state,
        make_lm_train_step,
        shift_labels,
    )
    from pytorch_distributed_tpu.train.lm_trainer import shard_lm_batch

    mesh = make_mesh(jax.devices()[:1], data_parallel=1, seq_parallel=1,
                     model_parallel=1)
    cfg = tiny_config(attention="dense")
    tx = build_optimizer("adamw", warmup_cosine(1e-3, 10_000),
                         weight_decay=0.0)

    def make_state():
        # fresh per timed run: the step donates its state argument
        state = create_lm_state(cfg, tx, jax.random.key(0))
        return jax.device_put(state, mesh_lib.replicated_sharding(mesh))

    step = make_lm_train_step(mesh, config=cfg)
    rng = np.random.default_rng(0)
    tokens = rng.integers(1, cfg.vocab_size, (2, 32)).astype(np.int32)
    labels, weights = shift_labels(tokens)
    batch = shard_lm_batch(mesh, {
        "tokens": tokens, "labels": labels, "weights": weights,
    })
    return mesh, make_state, step, batch


def _run(mode: str, mesh, state, step, batch, steps: int,
         log_every: int) -> float:
    from pytorch_distributed_tpu.parallel import mesh as mesh_lib
    from pytorch_distributed_tpu.telemetry import DeviceMetricsRing

    ring = None
    if mode == "ring":
        ring = DeviceMetricsRing(
            ["loss", "tokens"], capacity=8,
            sharding=mesh_lib.replicated_sharding(mesh),
        )
    # warmup (compile + donation settle + ring program), outside the
    # timed window
    for i in range(5):
        state, metrics = step(state, batch)
        if mode == "ring" and i == 0:
            ring.append(metrics, step=-1)
    if mode == "ring":
        ring.flush()
    float(metrics["loss"])  # drain before the clock starts
    t0 = time.perf_counter()
    for i in range(steps):
        state, metrics = step(state, batch)
        if i % log_every == 0:
            if mode == "ring":
                ring.append(metrics, step=i)
            elif mode == "blocking":
                float(metrics["loss"])  # the seed path's per-log sync
    if mode == "ring":
        ring.flush()
    float(jax.device_get(state.step))  # drain the dispatch queue
    return (time.perf_counter() - t0) / steps * 1e3


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--steps", type=int, default=600)
    p.add_argument("--repeats", type=int, default=3)
    p.add_argument("--log-every", type=int, default=100,
                   help="log cadence (TrainerConfig default 100)")
    args = p.parse_args()

    mesh, make_state, step, batch = _build()
    out: dict = {"telemetry_bench_steps": args.steps,
                 "telemetry_bench_log_every": args.log_every,
                 "device": str(jax.devices()[0])}
    for mode in ("off", "ring", "blocking"):
        ms = [
            _run(mode, mesh, make_state(), step, batch, args.steps,
                 args.log_every)
            for _ in range(args.repeats)
        ]
        out[f"telemetry_step_ms_{mode}"] = round(float(np.median(ms)), 4)
    off = out["telemetry_step_ms_off"]
    out["telemetry_ring_overhead_frac"] = round(
        (out["telemetry_step_ms_ring"] - off) / off, 4
    )
    out["telemetry_blocking_overhead_frac"] = round(
        (out["telemetry_step_ms_blocking"] - off) / off, 4
    )
    print(json.dumps(out))


if __name__ == "__main__":
    main()
