"""Wall-clock: zigzag vs contiguous causal ring layout (VERDICT r3
weak #5 — the zigzag win was proven by schedule counters only).

Runs the REAL LM train step (make_lm_train_step, ring attention) over an
8-virtual-CPU-device dp1×sp8 mesh with both layouts and times steps the
BENCH_PP way: chained steps inside one jit, differential trip-count slope
(scripts/bench_attention.difftime). On one physical core the 8 virtual
devices serialize, so wall-clock ≈ TOTAL block area; the zigzag win on a
real pod is in the MAX per-rank area (the critical path), which the
schedule counters in tests/test_sequence.py measure — both numbers are
reported here for the honest picture.

Usage: JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
       python scripts/bench_ring.py
Prints one JSON line per (layout) plus the counter-derived balance.
"""

from __future__ import annotations

import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np
from jax import lax

from pytorch_distributed_tpu.models.transformer import tiny_config
from pytorch_distributed_tpu.ops.optim import sgd_with_weight_decay
from pytorch_distributed_tpu.parallel import make_mesh
from pytorch_distributed_tpu.train.lm import (
    create_lm_state,
    make_lm_train_step,
    shard_lm_state,
)
from pytorch_distributed_tpu.train.lm_trainer import shard_lm_batch
from pytorch_distributed_tpu.train.lm import shift_labels

sys.path.insert(0, os.path.join(REPO, "scripts"))
from bench_attention import difftime  # noqa: E402


def bench_layout(layout: str, l: int = 2048, b: int = 1) -> float:
    mesh = make_mesh(jax.devices()[:8], data_parallel=1, seq_parallel=8)
    cfg = tiny_config(
        attention="ring", ring_layout=layout, max_seq_len=l,
        num_layers=2, num_heads=4, embed_dim=128,
    )
    tx = sgd_with_weight_decay(0.1, momentum=0.9)
    state = create_lm_state(cfg, tx, jax.random.key(0), init_len=32)
    state, specs = shard_lm_state(mesh, state, cfg)
    step = make_lm_train_step(mesh, state_specs=specs, config=cfg)

    rng = np.random.default_rng(0)
    tokens = rng.integers(1, 128, (b, l)).astype(np.int32)
    labels, weights = shift_labels(tokens)
    batch = shard_lm_batch(
        mesh, {"tokens": tokens, "labels": labels, "weights": weights},
        layout=layout,
    )

    # chain steps through the donated state inside one jit; consume a
    # scalar so nothing is dead code
    @jax.jit
    def chained(n):
        def body(i, carry):
            st, acc = carry
            st, m = step(st, batch)
            return st, acc + m["loss"] * 1e-30

        _, acc = lax.fori_loop(0, n, body, (state, jnp.float32(0)))
        return acc

    dt = difftime(chained, k1=2, k2=10)
    print(json.dumps({
        "ring_layout": layout, "L": l, "sp": 8,
        "step_ms": round(dt * 1e3, 1),
    }))
    return dt


def main() -> None:
    dt_c = bench_layout("contiguous")
    dt_z = bench_layout("zigzag")
    print(json.dumps({
        "ring_wallclock_ratio_zigzag_over_contiguous":
            round(dt_z / dt_c, 3),
        "note": "1-core CPU mesh serializes ranks: wall-clock tracks "
                "TOTAL area (expect ~parity); the pod-relevant win is the "
                "critical-path MAX measured by the schedule counters "
                "(tests/test_sequence.py: max halves at sp=8)",
    }))


if __name__ == "__main__":
    main()
