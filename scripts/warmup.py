"""Pre-warm a serving deployment's compile cache from the command line.

Builds the paged serving engine for an LM config, enumerates every
program it can ever run (``compilecache.serving_registry``: one chunk-
prefill program per (job-count, table-width) bucket + the decode tick),
compiles them all — populating jax's persistent compilation cache at
``--compile-cache-dir`` — and writes a warmup manifest JSONL
(``kind="warmup"`` records: program, seconds, backend-compile seconds,
cache_hit, fingerprint) that ``scripts/telemetry_report.py`` renders.

Run it once per (config, cache dir) before rolling out servers: the
first run compiles fresh and fills the cache; every later server start
(``recipes/serve_lm.py --warmup --compile-cache-dir ...``) — and every
re-run of this script — loads executables from disk instead of
recompiling. ``--expect-hits`` turns that into a gate: exit non-zero
unless at least one program was a cache hit (the ci_check.sh
``--warmup-smoke`` assertion that the cache actually persists).

    python scripts/warmup.py --tiny --compile-cache-dir /tmp/cc
    python scripts/warmup.py --tiny --compile-cache-dir /tmp/cc --expect-hits
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from pytorch_distributed_tpu.utils.env import (  # noqa: E402
    resolve_compile_cache_dir,
    set_env,
)


def _parse() -> argparse.Namespace:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--compile-cache-dir", default=None,
                   help="persistent compilation cache directory (env "
                        "fallback PDT_COMPILE_CACHE_DIR); required")
    p.add_argument("--manifest", default=None,
                   help="warmup manifest JSONL path (default "
                        "<cache-dir>/warmup_manifest.jsonl, appended)")
    p.add_argument("--tiny", action="store_true",
                   help="tiny LM config (CPU smoke; matches serve_lm)")
    p.add_argument("--max-seq-len", type=int, default=None,
                   help="override the config's max_seq_len")
    p.add_argument("--slots", type=int, default=8, help="decode lanes")
    p.add_argument("--block-len", type=int, default=16,
                   help="KV block length")
    p.add_argument("--prefill-chunk", type=int, default=32,
                   help="prefill chunk length")
    p.add_argument("--expect-hits", action="store_true",
                   help="exit non-zero unless >= 1 program was a "
                        "persistent-cache hit (warm-start gate)")
    p.add_argument("--json", action="store_true",
                   help="print the summary as one JSON line")
    return p.parse_args()


def main() -> int:
    args = _parse()
    cache_dir = resolve_compile_cache_dir(args.compile_cache_dir)
    if not cache_dir:
        print("--compile-cache-dir (or PDT_COMPILE_CACHE_DIR) is required:"
              " warming a cache needs somewhere to put it",
              file=sys.stderr)
        return 2

    set_env("202607")
    from pytorch_distributed_tpu.compilecache import (
        WarmupRunner,
        enable_persistent_cache,
        serving_registry,
    )

    enable_persistent_cache(cache_dir)

    import jax
    import jax.numpy as jnp

    from pytorch_distributed_tpu.models.transformer import (
        TransformerConfig,
        TransformerLM,
        tiny_config,
    )
    from pytorch_distributed_tpu.serving.engine import PagedEngine
    from pytorch_distributed_tpu.utils.profiling import MetricsLogger

    if args.tiny or jax.default_backend() == "cpu":
        cfg = tiny_config(attention="dense",
                          max_seq_len=args.max_seq_len or 128)
    else:
        cfg = TransformerConfig(
            vocab_size=32_000, num_layers=12, num_heads=12, embed_dim=768,
            max_seq_len=args.max_seq_len or 2048, attention="dense",
            dropout=0.0,
        )
    params = TransformerLM(cfg).init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    engine = PagedEngine(cfg, params, args.slots, block_len=args.block_len,
                         prefill_chunk=args.prefill_chunk)
    registry = serving_registry(engine)
    manifest_path = args.manifest or os.path.join(
        cache_dir, "warmup_manifest.jsonl"
    )
    with MetricsLogger(manifest_path) as manifest:
        runner = WarmupRunner(registry, manifest=manifest)
        # foreground everything: a standalone prewarmer has no traffic to
        # overlap with — priority order still drives the compile sequence
        runner.run(background=False)
    summary = runner.summary()
    summary["manifest"] = manifest_path
    if args.json:
        print(json.dumps(summary))
    else:
        print(
            f"warmed {summary['programs']} programs in "
            f"{summary['total_s']:.2f}s ({summary['cache_hits']} cache "
            f"hits, {summary['fresh']} fresh; backend compile "
            f"{summary['backend_compile_s']:.2f}s; fingerprint "
            f"{summary['fingerprint']})\nmanifest: {manifest_path}"
        )
    if args.expect_hits and summary["cache_hits"] < 1:
        print("--expect-hits: no persistent-cache hit — the cache at "
              f"{cache_dir} did not serve this config's programs",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
