"""Empirical bound on the --raw fast path's augmentation deviation.

The raw pipeline (data/raw.py) applies RandomResizedCrop to the STORED
center-crop instead of the original image — documented, but round 2
shipped no experiment bounding the accuracy effect (VERDICT r2 weak #7:
"the accuracy claim and the throughput claim ride different code
paths"). This trains the same tiny ResNet for a fixed budget on the SAME
underlying images through both pipelines and reports the val-accuracy
delta, at a scaled-down geometry (96px originals → 48px stored crop →
32px training crop, mirroring 512-ish → 256 → 224).

Synthetic but learnable data: each class is a 2-D sinusoid pattern with
class-dependent frequency/orientation plus noise, so accuracy is far
from chance and sensitive to what the crops see.

Run: JAX_PLATFORMS=cpu python scripts/exp_raw_accuracy.py
Emits one JSON line per (pipeline, seed) and a summary line.
"""

from __future__ import annotations

import io
import json
import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np

N_CLASSES = 8
N_TRAIN, N_VAL = 512, 256
ORIG, STORED, CROP = 96, 48, 32
STEPS, BATCH = 80, 32


def make_image(cls: int, rng: np.random.Generator) -> np.ndarray:
    """Class-dependent sinusoid + noise, uint8 HWC."""
    y, x = np.mgrid[0:ORIG, 0:ORIG] / ORIG
    freq = 2 + cls
    angle = cls * np.pi / N_CLASSES
    pattern = np.sin(2 * np.pi * freq * (x * np.cos(angle) + y * np.sin(angle)))
    img = np.stack([
        pattern,
        np.roll(pattern, cls, axis=0),
        -pattern,
    ], axis=-1)
    img = (img * 0.4 + 0.5) + rng.normal(0, 0.15, img.shape)
    return (np.clip(img, 0, 1) * 255).astype(np.uint8)


def jpeg_bytes(img: np.ndarray) -> bytes:
    from PIL import Image

    buf = io.BytesIO()
    Image.fromarray(img).save(buf, format="JPEG", quality=90)
    return buf.getvalue()


def build_splits(root: str):
    from pytorch_distributed_tpu.data.imagenet import write_imagenet_split
    from pytorch_distributed_tpu.data.raw import write_imagenet_raw_split

    rng = np.random.default_rng(0)
    for split, n in (("train", N_TRAIN), ("val", N_VAL)):
        imgs = []
        for i in range(n):
            cls = i % N_CLASSES
            imgs.append((jpeg_bytes(make_image(cls, rng)), cls))
        write_imagenet_split(os.path.join(root, f"{split}.tprc"), imgs)
        write_imagenet_raw_split(
            os.path.join(root, f"{split}.rawtprc"), imgs, image_size=STORED
        )


def run(root: str, pipeline: str, seed: int) -> float:
    from pytorch_distributed_tpu.data import transforms as T
    from pytorch_distributed_tpu.data.imagenet import ImageNet
    from pytorch_distributed_tpu.data.raw import RawImageNet
    from pytorch_distributed_tpu.data.sampler import DistributedSampler
    from pytorch_distributed_tpu.models.resnet import BasicBlock, ResNet
    from pytorch_distributed_tpu.ops.optim import sgd_with_weight_decay
    from pytorch_distributed_tpu.parallel import (
        replicated_sharding,
        shard_batch,
        single_device_mesh,
    )
    from pytorch_distributed_tpu.train.state import TrainState
    from pytorch_distributed_tpu.train.step import (
        make_eval_step,
        make_train_step,
    )

    if pipeline == "jpeg":
        train_tf = T.Compose([
            T.RandomResizedCrop(CROP), T.RandomHorizontalFlip(),
            T.Normalize(),
        ])
        eval_tf = T.Compose([T.Resize(STORED), T.CenterCrop(CROP),
                             T.Normalize()])
        train_ds = ImageNet("train", data_dir=root, transform=train_tf)
        val_ds = ImageNet("val", data_dir=root, transform=eval_tf)
    else:
        train_ds = RawImageNet("train", data_dir=root, crop_size=CROP,
                               aug="rrc")
        val_ds = RawImageNet("val", data_dir=root, crop_size=CROP,
                             aug="none")

    mesh = single_device_mesh()
    model = ResNet(stage_sizes=(1, 1), block_cls=BasicBlock,
                   num_classes=N_CLASSES, num_filters=8, dtype=jnp.float32)
    tx = sgd_with_weight_decay(0.05, momentum=0.9, weight_decay=1e-4)
    state = TrainState.create(model, tx, jax.random.key(seed),
                              (1, CROP, CROP, 3))
    state = jax.device_put(state, replicated_sharding(mesh))
    train_step = make_train_step(mesh)
    eval_step = make_eval_step(mesh)

    sampler = DistributedSampler(len(train_ds), seed=seed)
    loader = train_ds.loader(BATCH, sampler=sampler, num_workers=0,
                             drop_last=True)
    step = 0
    epoch = 0
    while step < STEPS:
        sampler.set_epoch(epoch)
        for host_batch in loader.iter_batches(0):
            state, _ = train_step(state, shard_batch(mesh, host_batch))
            step += 1
            if step >= STEPS:
                break
        epoch += 1

    from pytorch_distributed_tpu.ops.metrics import ClassificationMetrics

    metrics = jax.device_put(ClassificationMetrics.empty(),
                             replicated_sharding(mesh))
    vloader = val_ds.loader(BATCH, num_workers=0, drop_last=True)
    for host_batch in vloader.iter_batches(0):
        metrics = eval_step(state, shard_batch(mesh, host_batch), metrics)
    return float(jax.device_get(metrics).summary()["acc1"])


def main() -> None:
    with tempfile.TemporaryDirectory() as root:
        build_splits(root)
        accs = {"jpeg": [], "raw": []}
        for seed in (0, 1):
            for pipeline in ("jpeg", "raw"):
                acc = run(root, pipeline, seed)
                accs[pipeline].append(acc)
                print(json.dumps({"pipeline": pipeline, "seed": seed,
                                  "val_acc1": round(acc, 2)}), flush=True)
        mj = float(np.mean(accs["jpeg"]))
        mr = float(np.mean(accs["raw"]))
        print(json.dumps({
            "raw_accuracy_summary": {
                "jpeg_mean_acc1": round(mj, 2),
                "raw_mean_acc1": round(mr, 2),
                "delta_pp": round(mr - mj, 2),
                "steps": STEPS, "geometry": f"{ORIG}->{STORED}->{CROP}",
            }
        }))


if __name__ == "__main__":
    main()
