"""Transformer LM training throughput on the real chip.

Runs the REAL compiled LM train step (train/lm.py: shard_map over the
mesh, psum gradient combine, AdamW) on a GPT-2-small-shaped model with the
Pallas flash-attention kernel, measures tokens/s with the pipelined-
dispatch method (PERF_NOTES.md), and reports model FLOPs utilization via
the standard 6·N·tokens/s estimate. Also times the dense-attention variant
for the kernel's end-to-end contribution.

The reference has no LM at all (SURVEY.md §5: long-context ABSENT) — this
benchmarks capability the framework adds on top of parity.

Usage: python scripts/bench_lm.py [--quick]
Prints one JSON line per config.
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from pytorch_distributed_tpu.models.transformer import TransformerConfig
from pytorch_distributed_tpu.ops.optim import build_optimizer
from pytorch_distributed_tpu.parallel import make_mesh
from pytorch_distributed_tpu.train.lm import (
    create_lm_state,
    make_lm_train_step,
    shard_lm_state,
    shift_labels,
)

PEAK_TFLOPS = 197.0  # v5e bf16

# one definition of the tunnel round-trip correction for every bench
from bench import measure_roundtrip_s  # noqa: E402


def bench(attention: str, batch: int, seq: int, iters: int = 20,
          quiet: bool = False) -> dict:
    cfg = TransformerConfig(
        vocab_size=32000,
        num_layers=12,
        num_heads=12,
        embed_dim=768,
        max_seq_len=seq,
        dtype=jnp.bfloat16,
        attention=attention,
        block_size=512,
    )
    mesh = make_mesh(jax.devices()[:1])
    tx = build_optimizer("adamw", 3e-4, weight_decay=0.1)
    state = create_lm_state(cfg, tx, jax.random.key(0), init_len=seq)
    n_params = state.param_count()
    state, specs = shard_lm_state(mesh, state, cfg)
    step = make_lm_train_step(mesh, state_specs=specs, config=cfg)

    rng = np.random.default_rng(0)
    tokens = rng.integers(1, cfg.vocab_size, (batch, seq)).astype(np.int32)
    labels, weights = shift_labels(tokens)
    sh = NamedSharding(mesh, P("data", "seq"))
    b = {"tokens": jax.device_put(tokens, sh),
         "labels": jax.device_put(labels, sh),
         "weights": jax.device_put(weights, sh)}

    for _ in range(3):
        state, m = step(state, b)
    loss = float(m["loss"])
    assert np.isfinite(loss), loss
    # median of 3 windows (the BENCH_TABLE spread policy — a single
    # window samples the tunnel's weather); ONE roundtrip estimate for
    # all windows (per-window re-measurement costs ~4 tunnel hops each
    # and makes windows subtract inconsistent estimates)
    rt = measure_roundtrip_s()
    rates = []
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(iters):
            state, m = step(state, b)
        float(m["loss"])
        dt = time.perf_counter() - t0
        dt = max(dt - rt, dt / 2) / iters
        rates.append(batch * seq / dt)
    tok_s = float(np.median(rates))
    dt = batch * seq / tok_s
    # standard estimate: fwd+bwd ≈ 6 FLOPs/param/token + attention term
    attn_flops = 12 * cfg.num_layers * cfg.embed_dim * seq  # per token
    mfu = (6 * n_params + attn_flops) * tok_s / (PEAK_TFLOPS * 1e12)
    out = {
        "model": "gpt2-small-shaped", "params_m": round(n_params / 1e6, 1),
        "attention": attention, "batch": batch, "seq": seq,
        "step_ms": round(dt * 1e3, 2), "tokens_per_s": round(tok_s),
        "tokens_per_s_min": round(min(rates)),
        "tokens_per_s_max": round(max(rates)),
        "mfu": round(mfu, 3), "loss": round(loss, 3),
        "device": str(jax.devices()[0]),
    }
    if not quiet:  # bench.py reuses this and must print ONE json line total
        print(json.dumps(out))
    return out


def main():
    quick = "--quick" in sys.argv
    configs = [("flash", 8, 1024)]
    if not quick:
        configs += [("dense", 8, 1024), ("flash", 4, 4096), ("blockwise", 4, 4096)]
    for attention, batch, seq in configs:
        try:
            bench(attention, batch, seq)
        except Exception as e:
            print(json.dumps({"attention": attention, "batch": batch,
                              "seq": seq, "error": str(e)[:200]}))


if __name__ == "__main__":
    main()
