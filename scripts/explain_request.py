"""explain_request: reconstruct one request's causal story from JSONL.

Give it a rid and the telemetry JSONL(s) a serve run wrote
(``--metrics-out``; the ``kind="span"`` stream from
``telemetry.reqtrace``) and it replays the request's whole lifecycle as
a tree — where it waited, which replica served each phase, whether it
was handed off prefill→decode, whether it was preempted and why the
decision chose swap over recompute (predicted vs measured wall), and
each phase's wall next to the measured per-program cost cards
(``kind="program_cost"``, PR 8) where one applies. When the run also
carried the round-15 dispatch ledger (``kind="overlap"``), every decode
window is annotated with its device-busy vs bubble split — a SLOW
request (busy-dominated windows) reads differently from a STARVED one
(bubble-dominated: the device sat idle while its replica waited on the
host loop):

    python scripts/explain_request.py serve.jsonl --rid 17
    python scripts/explain_request.py serve.jsonl --find preempted
    python scripts/explain_request.py serve.jsonl --rid 17 --assert-complete
    python scripts/explain_request.py serve.jsonl --perfetto out.trace.json

``--find preempted|handed-off|shed|redispatched|failed|deadline|cancelled|any``
picks the first rid whose trace matches the predicate — the CI smoke
uses it to assert a preempted AND a handed-off request both left
complete traces without hard-coding rids; the round-19 predicates pick
out the failure plane (``redispatched`` = replayed off a dead replica,
with the replica-hop chain rendered under the tree; ``failed`` /
``deadline`` = root span closed with that terminal outcome). ``--assert-complete`` exits non-zero unless the trace
is a closed acyclic tree: every span ended exactly once, every parent
opened earlier in the same trace, exactly one root, no orphan events —
the ``scripts/ci_check.sh --trace-smoke`` gate. ``--perfetto`` writes
the whole stream as Chrome-trace JSON (one process per request, one
thread row per replica, flow arrows across the handoff) loadable in
Perfetto / chrome://tracing.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from pytorch_distributed_tpu.telemetry.reqtrace import (  # noqa: E402
    SpanNode,
    build_tree,
    save_chrome_trace,
    span_records,
    trace_rids,
    validate_trace,
)


def load_records(paths: List[str]) -> List[dict]:
    records = []
    for path in paths:
        # include the rotated generation first, as flightrec readers do
        for p in (f"{path}.1", path):
            if not os.path.exists(p):
                if p == path:
                    raise SystemExit(f"{path}: no such file")
                continue
            with open(p) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        records.append(json.loads(line))
                    except json.JSONDecodeError:
                        continue  # torn tail: a kill mid-write
    return records


# ---- predicates for --find -------------------------------------------------


def _trace_has(records: List[dict], rid: int, name: str,
               **attrs) -> bool:
    for r in span_records(records, rid):
        if r.get("name") != name:
            continue
        if all(r.get(k) == v for k, v in attrs.items()):
            return True
    return False


def _root_outcome(records: List[dict], rid: int) -> Optional[str]:
    """The rid's terminal outcome: the ``outcome`` attribute on the end
    record of its root span (``name="request"``, no parent). None when
    the root never closed — the trace is still open or torn."""
    recs = span_records(records, rid)
    roots = {r["span"] for r in recs
             if r.get("ev") == "begin" and r.get("name") == "request"}
    for r in recs:
        if r.get("ev") == "end" and r.get("span") in roots:
            return r.get("outcome")
    return None


FINDERS = {
    "preempted": lambda recs, rid: (
        _trace_has(recs, rid, "preempt")
        and _trace_has(recs, rid, "restore")
    ),
    "handed-off": lambda recs, rid: _trace_has(recs, rid, "handoff"),
    "shed": lambda recs, rid: _trace_has(recs, rid, "gate", action="shed"),
    # round-19 failure plane: requests that died with their replica and
    # replayed elsewhere, exhausted the attempt cap, or missed their SLO
    "redispatched": lambda recs, rid: _trace_has(recs, rid, "redispatch"),
    "failed": lambda recs, rid: _root_outcome(recs, rid) == "failed",
    "deadline": lambda recs, rid: _root_outcome(recs, rid) == "deadline",
    # round 22: requests cancelled mid-flight (client hung up on the
    # HTTP front door, or an explicit FleetRouter.cancel)
    "cancelled": lambda recs, rid: _root_outcome(recs, rid) == "cancelled",
    "any": lambda recs, rid: True,
}


def find_rid(records: List[dict], what: str) -> Optional[int]:
    pred = FINDERS[what]
    for rid in trace_rids(records):
        if pred(records, rid):
            return rid
    return None


# ---- rendering -------------------------------------------------------------


def _program_costs(records: List[dict]) -> dict:
    cards = {}
    for r in records:
        if r.get("kind") == "program_cost":
            cards[r["program"]] = r  # newest wins
    return cards


def _fmt_ms(seconds) -> str:
    return f"{seconds * 1e3:.2f}ms" if seconds is not None else "?"


def render_node(node: SpanNode, t_root: float, costs: dict,
                lines: List[str], depth: int = 0,
                device_splits: Optional[dict] = None) -> None:
    pad = "  " * depth
    rep = node.record.get("replica")
    where = f" [r{rep}]" if rep is not None else ""
    attrs = node.attrs()
    if node.is_event:
        detail = ", ".join(f"{k}={v}" for k, v in attrs.items())
        lines.append(
            f"{pad}· {node.name}{where} @+{_fmt_ms(node.t0 - t_root)}"
            + (f"  ({detail})" if detail else "")
        )
    else:
        dur = f" ({_fmt_ms(node.dur_s)})" if node.dur_s is not None \
            else "  [OPEN]"
        detail = ", ".join(f"{k}={v}" for k, v in attrs.items())
        cost = ""
        prog = attrs.get("program")
        if prog and prog in costs and costs[prog].get("mean_s"):
            card = costs[prog]
            # round-20 provenance: was this program serving with an
            # autotuned kernel config or the defaults? (annotated onto
            # every cost card by scheduler.log_cost_cards)
            cfg = ""
            if "tuned" in card:
                cfg = ", tuned cfg" if card["tuned"] else ", default cfg"
            cost = f"  [card: {_fmt_ms(card['mean_s'])}/call{cfg}]"
        split = ""
        span_id = node.record.get("span")
        if device_splits and span_id in device_splits:
            # the round-15 overlap join: this window's wall split into
            # device-busy vs bubble — a slow request (busy-dominated)
            # reads differently from a starved one (bubble-dominated)
            busy, bubble = device_splits[span_id]
            split = (f"  [device {_fmt_ms(busy)} busy / "
                     f"{_fmt_ms(bubble)} bubble]")
        lines.append(
            f"{pad}- {node.name}{where} +{_fmt_ms(node.t0 - t_root)}"
            f"{dur}" + (f"  {detail}" if detail else "") + cost + split
        )
    for child in node.children:
        render_node(child, t_root, costs, lines, depth + 1,
                    device_splits)


def phase_walls(root: SpanNode) -> dict:
    """Total wall per phase name across the tree (decode windows and
    repeated prefills sum) — the per-phase attribution line."""
    acc: dict = {}

    def walk(n: SpanNode):
        if not n.is_event and n.dur_s is not None and n is not root:
            acc[n.name] = acc.get(n.name, 0.0) + n.dur_s
        for c in n.children:
            walk(c)

    walk(root)
    return acc


def _device_splits(records: List[dict], rid: int) -> dict:
    """``{span_id: (busy_s, bubble_s)}`` for the rid's decode windows
    (round-15 overlap join): each window's wall intersected with its
    replica's device timeline. Empty when the run carried no
    ``kind="overlap"`` records — the annotation degrades away."""
    from pytorch_distributed_tpu.telemetry.overlap import (
        busy_within,
        overlap_records,
    )

    if not overlap_records(records, "launch"):
        return {}
    recs = span_records(records, rid)
    ends = {r["span"]: r for r in recs if r.get("ev") == "end"}
    splits = {}
    for r in recs:
        if r.get("ev") != "begin" or r.get("name") != "decode":
            continue
        end = ends.get(r["span"])
        if end is None:
            continue
        busy, bubble = busy_within(
            records, r.get("replica", 0), r.get("t", 0.0),
            end.get("t", 0.0),
        )
        splits[r["span"]] = (busy, bubble)
    return splits


def explain(records: List[dict], rid: int, out=None) -> int:
    """Render rid's causal story; returns 0, or 2 when the trace is
    missing entirely. ``out`` defaults to the CURRENT sys.stdout (late
    bound — an import-time default would pin whatever stream was active
    when the module first loaded, e.g. a pytest capture buffer)."""
    out = out if out is not None else sys.stdout
    recs = span_records(records, rid)
    if not recs:
        print(f"rid {rid}: no span records (was the run traced? "
              f"serve with --metrics-out and request tracing on)",
              file=sys.stderr)
        return 2
    errors = validate_trace(records, rid)
    root = build_tree(records, rid)
    costs = _program_costs(records)
    device_splits = _device_splits(records, rid)
    lines = [
        f"== request {rid} =="
        + (f"  [{len(errors)} completeness issue(s)]" if errors else
           "  [complete]")
    ]
    if root is None:
        lines.append("  (no root span — begin records only; partial "
                     "trace below)")
        for r in recs:
            lines.append(f"  {r}")
    else:
        render_node(root, root.t0, costs, lines,
                    device_splits=device_splits)
        walls = phase_walls(root)
        if walls:
            lines.append("per-phase wall: " + ", ".join(
                f"{name} {_fmt_ms(s)}" for name, s in
                sorted(walls.items(), key=lambda kv: -kv[1])
            ))
        if device_splits:
            busy = sum(b for b, _ in device_splits.values())
            bubble = sum(g for _, g in device_splits.values())
            total = busy + bubble
            lines.append(
                f"decode device split: {_fmt_ms(busy)} busy / "
                f"{_fmt_ms(bubble)} bubble"
                + (f" ({busy / total:.0%} busy)" if total > 0 else "")
                + " — a starved request is bubble-dominated, a slow "
                "one busy-dominated"
            )
        # the preempt audit: predicted vs measured, per sub-tree
        def preempts(n):
            if n.name == "preempt" and not n.is_event:
                yield n
            for c in n.children:
                yield from preempts(c)

        for p in preempts(root):
            a = p.attrs()
            swaps = [c for c in p.children
                     if c.name in ("swap_out", "swap_in")
                     and not c.is_event]
            measured = sum(c.attrs().get("wall_s") or 0.0 for c in swaps)
            lines.append(
                f"preempt audit: chose {a.get('decision')} "
                f"({a.get('decision_reason')}); predicted swap "
                f"{_fmt_ms(a.get('predicted_swap_s'))} vs recompute "
                + (_fmt_ms(a.get('predicted_recompute_s'))
                   if a.get('predicted_recompute_s') is not None
                   else "? (no measured chunk wall yet)")
                + (f"; measured swap {_fmt_ms(measured)}" if swaps else "")
            )
    # round-19 failure plane: the replica-hop chain — each hop is a
    # replica death that replayed this request elsewhere (``replayed``
    # counts already-delivered tokens re-prefilled, not regenerated)
    hops = [r for r in recs
            if r.get("ev") == "event" and r.get("name") == "redispatch"]
    if hops:
        chain = f"r{hops[0].get('src')}"
        for h in hops:
            chain += (f" ✝→ r{h.get('dst')} (attempt {h.get('attempt')},"
                      f" replayed {h.get('replayed')} tok)")
        lines.append(f"replica hops: {chain}")
    outcome = _root_outcome(records, rid)
    if outcome == "failed":
        lines.append("terminal outcome: FAILED — re-dispatch attempt "
                     "cap exhausted; the stream never completed")
    elif outcome == "deadline":
        lines.append("terminal outcome: DEADLINE — the request's SLO "
                     "budget lapsed before completion")
    elif outcome == "cancelled":
        lines.append("terminal outcome: CANCELLED — the caller hung up "
                     "(or cancelled explicitly); KV blocks freed "
                     "mid-flight")
    for e in errors:
        lines.append(f"INCOMPLETE: {e}")
    print("\n".join(lines), file=out)
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("paths", nargs="+", help="telemetry JSONL file(s)")
    p.add_argument("--rid", type=int, default=None,
                   help="request id to explain")
    p.add_argument("--find", choices=sorted(FINDERS), default=None,
                   help="pick the first rid whose trace matches the "
                        "predicate (preempted = preempt AND restore "
                        "events present; handed-off = a prefill→decode "
                        "handoff span)")
    p.add_argument("--assert-complete", action="store_true",
                   help="exit non-zero unless the trace is a closed, "
                        "acyclic, single-root span tree (CI gate)")
    p.add_argument("--perfetto", default=None, metavar="OUT",
                   help="also write the whole stream as Chrome-trace "
                        "JSON (Perfetto-loadable)")
    args = p.parse_args(argv)
    if (args.rid is None) == (args.find is None):
        p.error("exactly one of --rid / --find is required")

    records = load_records(args.paths)
    rid = args.rid
    if rid is None:
        rid = find_rid(records, args.find)
        if rid is None:
            print(f"--find {args.find}: no matching trace in "
                  f"{args.paths}", file=sys.stderr)
            return 2
        print(f"--find {args.find}: rid {rid}")
    rc = explain(records, rid)
    if rc:
        return rc
    if args.perfetto:
        path = save_chrome_trace(records, args.perfetto)
        print(f"perfetto trace: {path}")
    if args.assert_complete:
        errors = validate_trace(records, rid)
        if errors:
            print(f"--assert-complete: trace {rid} has "
                  f"{len(errors)} issue(s)", file=sys.stderr)
            return 2
        print(f"--assert-complete: trace {rid} OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
