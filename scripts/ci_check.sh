#!/usr/bin/env bash
# CI gate: static analysis first (cheap, seconds), then the tier-1 test
# suite from ROADMAP.md. jaxlint exits non-zero on any finding that is
# neither fixed, suppressed inline ('# jaxlint: disable=<rule> -- why'),
# nor recorded with a reason in scripts/jaxlint_baseline.json — so NEW
# hazards fail the build while the reviewed pre-existing ones don't.
#
# Usage: scripts/ci_check.sh [--lint-only|--lint-incremental|
#                             --resilience-smoke|--serving-smoke|
#                             --telemetry-smoke|--warmup-smoke|--reshard-smoke|
#                             --fleet-smoke|--obs-smoke|--kernel-smoke|
#                             --pressure-smoke|--trace-smoke|
#                             --overlap-smoke|--async-smoke|
#                             --prefix-smoke|--blocksan-smoke|
#                             --chaos-smoke|--tune-smoke|
#                             --soak-smoke|--gateway-smoke|
#                             --bench-regression]
#
# --lint-incremental: jaxlint via the content-hash cache
# (.jaxlint_cache.json) — unchanged files serve from cache, cross-module
# rules re-run on any change; the cheap per-commit gate. The full run
# (every other mode) stays the default and carries a 30 s timing budget
# plus a SARIF 2.1.0 artifact at output/jaxlint.sarif for CI annotation
# surfaces.
#
# --resilience-smoke: lint, then ONE crash-recovery cycle from the
# kill-matrix (SIGKILL mid-shard-write → relaunch → assert resume) —
# the cheap end-to-end proof that crash recovery still works, without
# the full tier-1 suite or the whole @crash matrix.
#
# --serving-smoke: lint, then ONE paged-engine submit/decode/drain
# cycle (tests/test_paged_serving.py::test_serving_smoke) — the cheap
# end-to-end proof the paged serving path still admits, decodes, and
# returns its blocks, without the parity/TP tier.
#
# --kernel-smoke: lint, then one pallas-gather serve cycle per pool
# dtype (int8/fp8; token-identical to generate; Pallas interpreter on
# CPU) + the int8/fp8 logit-error bounds + the split-S parity bound + a
# tiny --gather-ab run (A/B plumbing + JSON keys; the throughput claim
# itself is TPU-only).
#
# --tune-smoke: lint, then the round-20 autotuner cycle: the
# tests/test_autotune.py round-trip (sweep → persist → fresh engine
# loads by fingerprint with zero new jit-cache entries; stale
# fingerprint → clean miss), one scripts/autotune.py sweep, and the
# --gather-ab --tuned A/B consuming it.
#
# --telemetry-smoke: lint, then one short LM training run and one
# paged-serving cycle with --metrics-out, then telemetry_report.py must
# parse BOTH JSONLs and print a goodput breakdown + TTFT/per-token
# p50/p95 (it exits non-zero otherwise) — the end-to-end proof the
# observability pipeline (device ring → JSONL → report) still closes.
#
# --reshard-smoke: lint, then ONE cross-topology kill-and-resume cycle
# (SIGKILL a run on mesh (4,1,2) mid-save, relaunch it on (2,1,2) at the
# same global batch → elastic resume must reshard the checkpoint and
# finish the run) — the cheap end-to-end proof that a preempted run can
# resume on whatever topology the scheduler hands back, without the
# full cross-topology kill matrix.
#
# --fleet-smoke: lint, then the round-10 fleet cycle on one short seeded
# bursty trace: a 2-replica router (session affinity + SLO gate) and a
# disaggregated prefill/decode pair (KV-block handoff) both serve the
# trace through recipes/serve_lm.py, and telemetry_report.py must print
# the fleet section (per-replica percentiles, shed/spill rates) from
# their JSONLs — the cheap end-to-end proof the fleet layer still
# routes, hands off, and reports (~15 s).
#
# --obs-smoke: lint, then the round-11 attribution/forensics cycle: one
# tiny LM run with a seeded train.step HANG (the sentinel must flag it)
# and --cost-cards, a second tiny LM run with a seeded SUSPEND (the
# flight recorder must leave an atomic dump), and one serve cycle with
# --cost-cards — then telemetry_report.py must render the per-program
# MFU/roofline table and >=1 anomaly (--require cost,anomaly) and the
# flight-recorder dump must parse (~30 s).
#
# --pressure-smoke: lint, then the round-13 KV pressure cycle: one
# short over-committed serve (2-replica fleet, a pool holding ~3 chains
# per replica, bursty trace, tight shed bound, --preempt) must finish
# with >=1 preempt AND >=1 restore AND ZERO sheds (the preempt rung
# replacing the reject), then telemetry_report.py must render the
# pressure section (--require pressure: preempt rate, swap p95,
# decision crossover) from the JSONL alone (~30 s).
#
# --trace-smoke: lint, then the round-14 request-lifecycle tracing
# cycle: one disaggregated 2-replica serve (prefill/decode split, small
# decode pool, --preempt --swap-policy swap so the handoff pump's
# pressure rung forces at least one swap-path preemption) over a seeded
# bursty trace, then explain_request.py --assert-complete must
# reconstruct a single closed acyclic span tree for BOTH a preempted
# AND a handed-off rid (found by predicate, not hard-coded), a
# Perfetto-loadable Chrome trace must parse, and telemetry_report.py
# must render the request-trace section (--require spans) (~20 s).
#
# --overlap-smoke: lint, then the round-15 host–device overlap cycle:
# a short seeded trace through the wall-clock fleet driver
# (bench_serving.py --wall-clock: 2-replica vs 1-replica saturated
# throughput with the dispatch ledger armed) must report per-replica
# device-busy fractions and a bubble-cause histogram accounting for
# >=90% of the measured 1→2 efficiency gap; telemetry_report.py must
# render the overlap section (--require overlap) from the kept JSONL;
# and explain_request.py must show a decode window's device-busy vs
# bubble split on a complete trace (~30 s).
#
# --async-smoke: lint, then the round-16 async host runtime cycle:
# a short seeded trace through bench_serving.py --wall-clock (which now
# A/Bs the synchronous loop against the dispatch-then-collect loop on
# the same trace) must report the async side's decomposed gap
# accounting >=90% with the other-replica-tick share of the apportioned
# bubble histogram below 0.6 (the sync one-loop baseline attributed
# ~all bubble seconds to it); then explain_request.py
# --assert-complete must close a span tree from the ASYNC run's JSONL
# (worker-thread emission must not tear traces) and telemetry_report.py
# must render both the overlap and spans sections from it (~40 s).
#
# --prefix-smoke: lint, then the round-17 prefix-sharing cycle: one
# short seeded shared-system-prompt trace through the 2-replica
# session-affinity fleet with the radix prefix cache OFF then ON
# (bench_serving.py --prefix) must report hit rate > 0, a >= 1.5x
# admitted-prefill-token reduction, and BIT-IDENTICAL greedy token
# streams across the A/B; then telemetry_report.py must render the
# prefix section (--require prefix: hit rate, covered fraction, COW
# count) from the ON run's JSONL alone (~40 s).
#
# --blocksan-smoke: lint, then the round-18 block-lifecycle sanitizer
# cycle: one short disaggregated serve under PDT_BLOCKSAN=1 (preempt +
# swap so the trace crosses admit/prefix-share/COW/swap/restore/handoff/
# retire), then the SAME serve with an injected kv.swap_out_d2h fault —
# both runs' JSONLs must carry kind="sanitizer" quiesce records with
# ok=true and ZERO violation records (the shadow ledger matched the
# allocator even through the fault) (~40 s).
#
# --chaos-smoke: lint, then the round-19 replica-failure cycle: one
# 2-replica serve under PDT_BLOCKSAN=1 with an injected serve.dispatch
# kill (replica dies mid-flight, every stream recovers via re-dispatch)
# plus an already-expired admission (deadline shed), streamed to JSONL —
# then explain_request.py must find a redispatched rid by predicate,
# render its replica-hop chain, and close its span tree, and find the
# deadline rid's terminal outcome; the fleet_summary must carry the
# failure-plane counters. The fast chaos grid itself rides tier-1
# (tests/test_chaos_matrix.py, non-@slow); the full fault×state grid is
# @slow (~30 s).
#
# --soak-smoke: lint, then the round-21 scale-observatory cycle in
# miniature: ~2k heavy-tail sessions streamed through the 2-replica
# fleet with retention off (bench_serving.py --soak), the host-resource
# monitor + structure census + growth sentinel armed, and the metrics
# log capped small enough to force a rotation — the run must finish
# with the census verdict ok (zero bound violations, zero undeclared
# containers), a non-growing RSS verdict, and telemetry_report.py must
# render the resource AND census sections from the rotated JSONL alone
# (--require resource,census). The 100k-session run this miniaturizes
# is the @slow soak + the BENCH_r09 row (~60 s).
#
# --gateway-smoke: lint, then the round-22 HTTP front-door cycle under
# the block sanitizer: a 2-replica async fleet behind gateway.Gateway
# on an ephemeral port serves one request to completion over SSE and
# one that hangs up after its first token — the disconnect must reach
# FleetRouter.cancel (blocks freed; the drain's fleet-wide ledger
# quiesce proves it leak-free), explain_request.py --find cancelled
# must reconstruct the hung-up request's span tree closed
# outcome=cancelled, and telemetry_report.py must render the ingress
# section from the kind="http" records (--require http).
#
# --bench-regression: lint, then compare the two newest BENCH_r0N.json
# rounds key-by-key with per-key noise bands (scripts/bench_regression.py
# --auto); exits non-zero on any regression outside its band. Optional —
# run it when a new BENCH round lands.
#
# --warmup-smoke: lint, then the compile-cache round trip: prewarm a tiny
# LM serving registry into a fresh cache (scripts/warmup.py), re-run the
# prewarmer with --expect-hits (every program must now load from the
# persistent cache), then a cold-vs-warm serve cycle via
# scripts/bench_coldstart.py asserting the warm run's goodput compile
# fraction is below the cold run's (the full >=5x gate is
# bench_coldstart's default; the smoke uses --min-ratio 1.0 so a
# contended CI core can't flake it).
set -euo pipefail
cd "$(dirname "$0")/.."

if [[ "${1:-}" == "--lint-incremental" ]]; then
    echo "== jaxlint (incremental, content-hash cache) =="
    JAX_PLATFORMS=cpu python scripts/jaxlint.py --incremental \
        pytorch_distributed_tpu/
    exit 0
fi

echo "== jaxlint (full tree, 30s budget, SARIF artifact) =="
mkdir -p output
JAX_PLATFORMS=cpu python scripts/jaxlint.py pytorch_distributed_tpu/ \
    --sarif-out output/jaxlint.sarif --max-seconds 30

if [[ "${1:-}" == "--lint-only" ]]; then
    exit 0
fi

if [[ "${1:-}" == "--resilience-smoke" ]]; then
    echo "== resilience smoke (kill mid-shard-write, relaunch, resume) =="
    JAX_PLATFORMS=cpu python -m pytest tests/test_resilience.py -q \
        -m crash -k shard_write -p no:cacheprovider -p no:xdist \
        -p no:randomly
    exit 0
fi

if [[ "${1:-}" == "--reshard-smoke" ]]; then
    echo "== reshard smoke (kill on mesh (4,2), elastic resume on (2,2)) =="
    JAX_PLATFORMS=cpu python -m pytest \
        tests/test_reshard.py::test_reshard_smoke_kill_and_cross_mesh_resume \
        -q -p no:cacheprovider -p no:xdist -p no:randomly
    exit 0
fi

if [[ "${1:-}" == "--serving-smoke" ]]; then
    echo "== serving smoke (paged submit → decode → drain) =="
    JAX_PLATFORMS=cpu python -m pytest \
        tests/test_paged_serving.py::test_serving_smoke -q \
        -p no:cacheprovider -p no:xdist -p no:randomly
    exit 0
fi

if [[ "${1:-}" == "--kernel-smoke" ]]; then
    echo "== kernel smoke (pallas gather + quantized pools + split-S) =="
    # one full pallas-path serve cycle per pool dtype, token-identical
    # to the generate reference (interpret mode on CPU), the int8/fp8
    # logit-error bounds, the split-S-vs-single-worker parity bound,
    # then the gather A/B on the tiny model as a plumbing/JSON-schema
    # sanity check (the pallas>=dense throughput claim is TPU-only; the
    # CPU run exercises the same code path through the Pallas
    # interpreter)
    JAX_PLATFORMS=cpu python -m pytest \
        tests/test_paged_kernel.py::test_kernel_smoke \
        tests/test_paged_kernel.py::test_int8_pool_logit_error_bound \
        tests/test_paged_kernel.py::test_fp8_pool_logit_error_bound \
        tests/test_paged_kernel.py::test_fp8_serve_cycle_split_s \
        tests/test_paged_kernel.py::test_split_s_matches_single_worker -q \
        -p no:cacheprovider -p no:xdist -p no:randomly
    JAX_PLATFORMS=cpu python scripts/bench_serving.py --gather-ab --tiny \
        --ab-slots 4 --ab-ticks 8 --ab-prompt-len 32
    exit 0
fi

if [[ "${1:-}" == "--tune-smoke" ]]; then
    echo "== tune smoke (sweep -> tuned reload by fingerprint -> stale miss) =="
    # one tiny autotune sweep, then: (a) a fresh engine with the same
    # shape must LOAD the tuned config (tests assert zero new jit-cache
    # entries + registry coverage), (b) a different shape (stale
    # fingerprint) must miss CLEANLY — default config, no crash —
    # then the --tuned gather A/B prints the tuned-vs-default columns
    smoke=$(mktemp -d)
    trap 'rm -rf "$smoke"' EXIT
    JAX_PLATFORMS=cpu python -m pytest tests/test_autotune.py -q \
        -p no:cacheprovider -p no:xdist -p no:randomly
    JAX_PLATFORMS=cpu python scripts/autotune.py --tiny \
        --out-dir "$smoke/tuned" --block-lens 8,16 --split-ss 1,2 \
        --ticks 4 --prompt-len 16 --slots 4
    JAX_PLATFORMS=cpu python scripts/bench_serving.py --gather-ab --tiny \
        --ab-slots 4 --ab-ticks 8 --ab-prompt-len 32 \
        --tuned --autotune-dir "$smoke/tuned"
    exit 0
fi

if [[ "${1:-}" == "--fleet-smoke" ]]; then
    echo "== fleet smoke (trace -> 2-replica router + disagg P/D -> report) =="
    smoke=$(mktemp -d)
    trap 'rm -rf "$smoke"' EXIT
    JAX_PLATFORMS=cpu python scripts/bench_serving.py \
        --gen-trace "$smoke/trace.jsonl" --trace-duration 30 \
        --trace-base-rate 0.5 --trace-prompt-max 88
    JAX_PLATFORMS=cpu python recipes/serve_lm.py --tiny --replicas 2 \
        --slots 4 --max-new 8 --trace "$smoke/trace.jsonl" \
        --slo-ttft-ms 5000 --metrics-out "$smoke/fleet.jsonl"
    JAX_PLATFORMS=cpu python recipes/serve_lm.py --tiny --replicas 2 \
        --disaggregate --slots 4 --max-new 8 \
        --trace "$smoke/trace.jsonl" --metrics-out "$smoke/disagg.jsonl"
    JAX_PLATFORMS=cpu python scripts/telemetry_report.py \
        "$smoke/fleet.jsonl" "$smoke/disagg.jsonl" --json --require fleet
    exit 0
fi

if [[ "${1:-}" == "--warmup-smoke" ]]; then
    echo "== warmup smoke (prewarm → cache-hit gate → cold-vs-warm serve) =="
    smoke=$(mktemp -d)
    trap 'rm -rf "$smoke"' EXIT
    JAX_PLATFORMS=cpu python scripts/warmup.py --tiny \
        --compile-cache-dir "$smoke/cc" --slots 4 --json
    JAX_PLATFORMS=cpu python scripts/warmup.py --tiny \
        --compile-cache-dir "$smoke/cc" --slots 4 --expect-hits --json
    JAX_PLATFORMS=cpu python scripts/bench_coldstart.py --mode serve \
        --requests 24 --max-new 16 --min-ratio 1.0 \
        --json "$smoke/coldstart.json"
    JAX_PLATFORMS=cpu python scripts/telemetry_report.py \
        "$smoke/cc/warmup_manifest.jsonl" --json --require warmup
    exit 0
fi

if [[ "${1:-}" == "--pressure-smoke" ]]; then
    echo "== pressure smoke (over-committed serve -> preempt+restore, zero sheds) =="
    smoke=$(mktemp -d)
    trap 'rm -rf "$smoke"' EXIT
    JAX_PLATFORMS=cpu python scripts/bench_serving.py \
        --gen-trace "$smoke/trace.jsonl" --trace-duration 30 \
        --trace-base-rate 0.7 --trace-prompt-max 88
    JAX_PLATFORMS=cpu python recipes/serve_lm.py --tiny --replicas 2 \
        --slots 4 --n-blocks 13 --max-new 8 --preempt \
        --slo-shed-depth 4 --trace "$smoke/trace.jsonl" \
        --metrics-out "$smoke/pressure.jsonl"
    python - "$smoke/pressure.jsonl" <<'PY'
import json, sys
records = [json.loads(l) for l in open(sys.argv[1]) if l.strip()]
fleet = [r for r in records if r.get("kind") == "fleet_summary"][-1]
assert fleet["shed"] == 0, f"pressure tier shed {fleet['shed']} requests"
assert fleet["preempts"] >= 1, "over-committed cycle never preempted"
assert fleet["restores"] >= 1, "no preempted request was restored"
assert fleet["restores"] == fleet["preempts"], fleet
print(f"pressure: {fleet['preempts']} preempts, {fleet['restores']} "
      f"restores, 0 sheds, {fleet['swap_bytes']} swap bytes")
PY
    JAX_PLATFORMS=cpu python scripts/telemetry_report.py \
        "$smoke/pressure.jsonl" --json --require pressure
    exit 0
fi

if [[ "${1:-}" == "--trace-smoke" ]]; then
    echo "== trace smoke (disagg serve + forced preempt -> causal traces) =="
    smoke=$(mktemp -d)
    trap 'rm -rf "$smoke"' EXIT
    JAX_PLATFORMS=cpu python scripts/bench_serving.py \
        --gen-trace "$smoke/trace.jsonl" --trace-duration 30 \
        --trace-base-rate 0.7 --trace-prompt-max 88
    JAX_PLATFORMS=cpu python recipes/serve_lm.py --tiny --replicas 2 \
        --disaggregate --slots 4 --n-blocks 13 --max-new 8 \
        --preempt --swap-policy swap --trace "$smoke/trace.jsonl" \
        --metrics-out "$smoke/spans.jsonl"
    JAX_PLATFORMS=cpu python scripts/explain_request.py \
        "$smoke/spans.jsonl" --find handed-off --assert-complete
    JAX_PLATFORMS=cpu python scripts/explain_request.py \
        "$smoke/spans.jsonl" --find preempted --assert-complete \
        --perfetto "$smoke/requests.trace.json"
    python - "$smoke/requests.trace.json" <<'PY'
import json, sys
trace = json.load(open(sys.argv[1]))
events = trace["traceEvents"]
assert any(e.get("ph") == "X" for e in events), "no complete spans"
assert any(e.get("ph") == "s" for e in events), "no handoff flow arrows"
print(f"perfetto trace: {len(events)} events OK")
PY
    JAX_PLATFORMS=cpu python scripts/telemetry_report.py \
        "$smoke/spans.jsonl" --json --require spans
    exit 0
fi

if [[ "${1:-}" == "--overlap-smoke" ]]; then
    echo "== overlap smoke (wall-clock 1r-vs-2r -> bubbles account the gap) =="
    smoke=$(mktemp -d)
    trap 'rm -rf "$smoke"' EXIT
    JAX_PLATFORMS=cpu python scripts/bench_serving.py \
        --gen-trace "$smoke/trace.jsonl" --trace-duration 30 \
        --trace-base-rate 0.5 --trace-prompt-max 88
    JAX_PLATFORMS=cpu python scripts/bench_serving.py --wall-clock \
        --trace "$smoke/trace.jsonl" --wc-out "$smoke/overlap.jsonl" \
        > "$smoke/wallclock.json"
    python - "$smoke/wallclock.json" <<'PY'
import json, sys
row = json.loads(open(sys.argv[1]).read().strip().splitlines()[-1])
assert row["serving_wallclock_tok_s_1r"] > 0, row
assert row["serving_wallclock_tok_s_nr"] > 0, row
assert "serving_wallclock_device_busy_frac_r0" in row, sorted(row)
assert "serving_wallclock_device_busy_frac_r1" in row, sorted(row)
acc = row["serving_wallclock_gap_accounted_frac"]
assert acc >= 0.9, f"bubbles account for only {acc:.0%} of the gap"
causes = [k for k in row if k.startswith("serving_wallclock_bubble_")
          and k.endswith("_s")]
assert causes, "no bubble-cause histogram keys"
print(f"wall-clock: {row['serving_wallclock_tok_s_1r']} tok/s 1r vs "
      f"{row['serving_wallclock_tok_s_nr']} tok/s 2r "
      f"(backend={row['serving_wallclock_backend']}), "
      f"gap accounted {acc:.0%}, causes={len(causes)}")
PY
    JAX_PLATFORMS=cpu python scripts/telemetry_report.py \
        "$smoke/overlap.jsonl" --json --require overlap
    JAX_PLATFORMS=cpu python scripts/explain_request.py \
        "$smoke/overlap.jsonl" --find any --assert-complete \
        | tee "$smoke/explain.txt"
    grep -q "busy /" "$smoke/explain.txt" \
        || { echo "explain output missing the device busy/bubble split"; exit 1; }
    exit 0
fi

if [[ "${1:-}" == "--async-smoke" ]]; then
    echo "== async smoke (sync-vs-async wall-clock A/B -> honest histogram -> traces) =="
    smoke=$(mktemp -d)
    trap 'rm -rf "$smoke"' EXIT
    JAX_PLATFORMS=cpu python scripts/bench_serving.py \
        --gen-trace "$smoke/trace.jsonl" --trace-duration 30 \
        --trace-base-rate 0.5 --trace-prompt-max 88
    JAX_PLATFORMS=cpu python scripts/bench_serving.py --wall-clock \
        --trace "$smoke/trace.jsonl" --wc-out "$smoke/async.jsonl" \
        > "$smoke/wallclock.json"
    python - "$smoke/wallclock.json" <<'PY'
import json, sys
row = json.loads(open(sys.argv[1]).read().strip().splitlines()[-1])
assert row["serving_wallclock_async_tok_s_nr"] > 0, row
acc = row["serving_wallclock_async_gap_accounted_frac"]
assert acc >= 0.9, f"async gap accounted only {acc:.0%}"
share = row["serving_wallclock_async_other_replica_share"]
# the sync one-loop attributed ~all bubble seconds to the other
# replica's host work; the async loop's apportioned histogram must
# keep it below this threshold (at 2 replicas the irreducible
# shared-loop floor is ~half of the remaining host-bound bubbles)
assert share < 0.6, f"other-replica-tick still {share:.0%} of bubbles"
assert "serving_wallclock_async_device_busy_frac_union" in row, sorted(row)
print(f"async smoke: sync {row['serving_wallclock_tok_s_nr']} tok/s vs "
      f"async {row['serving_wallclock_async_tok_s_nr']} tok/s "
      f"(ratio {row['serving_wallclock_ratio_async_over_sync']}), "
      f"other-replica share {share:.0%}, gap accounted {acc:.0%}, "
      f"backend={row['serving_wallclock_backend']}")
PY
    JAX_PLATFORMS=cpu python scripts/explain_request.py \
        "$smoke/async.jsonl" --find any --assert-complete > /dev/null
    JAX_PLATFORMS=cpu python scripts/telemetry_report.py \
        "$smoke/async.jsonl" --json --require overlap,spans > /dev/null
    echo "async smoke OK"
    exit 0
fi

if [[ "${1:-}" == "--prefix-smoke" ]]; then
    echo "== prefix smoke (shared-prompt trace -> radix reuse A/B -> report) =="
    smoke=$(mktemp -d)
    trap 'rm -rf "$smoke"' EXIT
    JAX_PLATFORMS=cpu python scripts/bench_serving.py \
        --gen-trace "$smoke/trace.jsonl" --trace-duration 30 \
        --trace-base-rate 0.5 --trace-sessions 8 \
        --trace-prompt-median 12 --trace-prompt-max 32 \
        --trace-max-new-median 6 --trace-max-new-max 12
    JAX_PLATFORMS=cpu python scripts/bench_serving.py --prefix \
        --trace "$smoke/trace.jsonl" --prefix-out "$smoke/prefix.jsonl" \
        > "$smoke/prefix.json"
    python - "$smoke/prefix.json" <<'PY'
import json, sys
row = json.loads(open(sys.argv[1]).read().strip().splitlines()[-1])
assert row["serving_prefix_hit_rate"] > 0, row
assert row["serving_prefix_tokens_identical"] is True, \
    "prefix sharing changed a greedy token stream"
ratio = row["serving_prefix_admit_tok_ratio_off_over_on"]
assert ratio >= 1.5, f"admitted-prefill tokens only {ratio}x lower"
print(f"prefix: hit rate {row['serving_prefix_hit_rate']:.0%}, "
      f"admitted-prefill tokens {ratio}x lower, "
      f"{row['serving_prefix_cow_copies']} cow copies, tokens identical "
      f"(backend={row['serving_prefix_backend']})")
PY
    JAX_PLATFORMS=cpu python scripts/telemetry_report.py \
        "$smoke/prefix.jsonl" --json --require prefix > /dev/null
    echo "prefix smoke OK"
    exit 0
fi

if [[ "${1:-}" == "--blocksan-smoke" ]]; then
    echo "== blocksan smoke (PDT_BLOCKSAN=1 serve, clean + faulted -> ledger ok) =="
    smoke=$(mktemp -d)
    trap 'rm -rf "$smoke"' EXIT
    JAX_PLATFORMS=cpu python scripts/bench_serving.py \
        --gen-trace "$smoke/trace.jsonl" --trace-duration 30 \
        --trace-base-rate 0.7 --trace-prompt-max 88
    # clean pass: disagg + preempt/swap so the ledger sees every
    # lifecycle edge (alloc, share, COW, swap-out/in, handoff, retire)
    JAX_PLATFORMS=cpu PDT_BLOCKSAN=1 python recipes/serve_lm.py --tiny \
        --replicas 2 --disaggregate --slots 4 --n-blocks 13 --max-new 8 \
        --preempt --swap-policy swap --trace "$smoke/trace.jsonl" \
        --metrics-out "$smoke/blocksan.jsonl"
    # faulted pass: first swap-out D2H gather dies mid-window — the
    # revert path must leave the ledger just as clean
    JAX_PLATFORMS=cpu PDT_BLOCKSAN=1 \
        PDT_FAULT_PLAN='{"faults":[{"site":"kv.swap_out_d2h","kind":"raise","at":1}]}' \
        python recipes/serve_lm.py --tiny \
        --replicas 2 --disaggregate --slots 4 --n-blocks 13 --max-new 8 \
        --preempt --swap-policy swap --trace "$smoke/trace.jsonl" \
        --metrics-out "$smoke/blocksan_fault.jsonl"
    python - "$smoke/blocksan.jsonl" "$smoke/blocksan_fault.jsonl" <<'PY'
import json, sys
for path in sys.argv[1:]:
    rows = [json.loads(l) for l in open(path) if l.strip()]
    san = [r for r in rows if r.get("kind") == "sanitizer"]
    bad = [r for r in san if r["ev"] == "violation"]
    quiesce = [r for r in san if r["ev"] == "quiesce"]
    assert not bad, f"{path}: blocksan violations: {bad}"
    assert quiesce, f"{path}: no quiesce record — sanitizer never armed"
    assert all(q["ok"] for q in quiesce), quiesce
    print(f"{path.rsplit('/', 1)[-1]}: {len(quiesce)} quiesce record(s) "
          f"ok, 0 violations")
PY
    echo "blocksan smoke OK"
    exit 0
fi

if [[ "${1:-}" == "--chaos-smoke" ]]; then
    echo "== chaos smoke (replica kill -> re-dispatch + deadline shed -> explain) =="
    smoke=$(mktemp -d)
    trap 'rm -rf "$smoke"' EXIT
    JAX_PLATFORMS=cpu python - "$smoke/chaos.jsonl" <<'PY'
import os
import sys

os.environ["PDT_BLOCKSAN"] = "1"
import jax
import jax.numpy as jnp
import numpy as np

from pytorch_distributed_tpu.fleet import FleetRouter
from pytorch_distributed_tpu.models.transformer import (
    TransformerLM, tiny_config,
)
from pytorch_distributed_tpu.resilience import faults
from pytorch_distributed_tpu.resilience.faults import FaultPlan, FaultSpec
from pytorch_distributed_tpu.telemetry.reqtrace import ReqTracer
from pytorch_distributed_tpu.utils.profiling import MetricsLogger

cfg = tiny_config(attention="dense", max_seq_len=96)
params = TransformerLM(cfg).init(
    jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
)["params"]
mlog = MetricsLogger(sys.argv[1])
router = FleetRouter(
    cfg, params, n_replicas=2, n_slots=3, block_len=8, prefill_chunk=8,
    fail_threshold=1, metrics_log=mlog, reqtrace=ReqTracer(sink=mlog),
)
rng = np.random.default_rng(0)
prompts = [rng.integers(1, cfg.vocab_size, (9 + i,)).astype(np.int32)
           for i in range(3)]
faults.install_plan(FaultPlan([
    FaultSpec(site="serve.dispatch", kind="raise", at=2, times=1)
]))
try:
    rids = [router.submit(p, 6) for p in prompts]
    # a request whose budget is already spent sheds at admission
    expired = router.submit(prompts[0], 6, deadline_s=-0.01)
    out = router.drain(max_steps=4000)
finally:
    faults.clear_plan()
assert all(len(out[r]) == 6 for r in rids), "a stream did not recover"
assert router.rejected[expired] == "deadline-expired"
m = router.metrics()
assert m["replica_deaths"] == 1 and m["redispatched"] >= 1, m
router.blocksan.assert_clean()
router.log_summary()
mlog.close()
print(f"chaos serve: {len(rids)} streams recovered off a dead replica, "
      f"1 deadline shed, ledger clean")
PY
    JAX_PLATFORMS=cpu python scripts/explain_request.py \
        "$smoke/chaos.jsonl" --find redispatched --assert-complete \
        | tee "$smoke/explain.txt"
    grep -q "replica hops:" "$smoke/explain.txt" \
        || { echo "explain output missing the replica-hop chain"; exit 1; }
    JAX_PLATFORMS=cpu python scripts/explain_request.py \
        "$smoke/chaos.jsonl" --find deadline --assert-complete \
        > "$smoke/deadline.txt"
    grep -q "terminal outcome: DEADLINE" "$smoke/deadline.txt" \
        || { echo "explain output missing the deadline outcome"; exit 1; }
    python - "$smoke/chaos.jsonl" <<'PY'
import json, sys
rows = [json.loads(l) for l in open(sys.argv[1]) if l.strip()]
from pytorch_distributed_tpu.telemetry.schema import validate_stream
assert validate_stream(rows) == [], validate_stream(rows)[:5]
health = [r for r in rows if r.get("kind") == "health"]
assert {"draining", "dead"} <= {r["state"] for r in health}, health
fleet = [r for r in rows if r.get("kind") == "fleet_summary"][-1]
assert fleet["replica_deaths"] == 1 and fleet["redispatched"] >= 1
print(f"telemetry: {len(health)} health transitions on the wire, "
      f"fleet_summary carries the failure plane")
PY
    echo "chaos smoke OK"
    exit 0
fi

if [[ "${1:-}" == "--soak-smoke" ]]; then
    echo "== soak smoke (2k-session stream -> census ok, flat RSS, report) =="
    smoke=$(mktemp -d)
    trap 'rm -rf "$smoke"' EXIT
    # small log cap so the rotation path is exercised, not just present
    JAX_PLATFORMS=cpu python scripts/bench_serving.py --soak \
        --soak-requests 2000 --soak-log "$smoke/soak.jsonl" \
        --soak-log-mb 0.25 > "$smoke/soak.json"
    python - "$smoke/soak.json" "$smoke/soak.jsonl" <<'PY'
import json, os, sys
row = json.loads(open(sys.argv[1]).read().strip().splitlines()[-1])
assert row["serving_soak_sessions"] == 2000, row["serving_soak_sessions"]
assert row["serving_soak_census_verdict"] == "ok", row
assert row["serving_soak_census_violations"] == 0, row
assert row["serving_soak_census_undeclared"] == 0, row
assert row["serving_soak_undeclared_at_start"] == 0, row
# 2k sessions is far too short for a slope claim; the gate is only
# that the sentinel did not see runaway growth at this scale
assert row["serving_soak_rss_verdict"] in ("flat", "linear", "insufficient"), row
assert row["serving_soak_rss_slope_mib_per_10k"] < 50.0, row
assert row["serving_soak_results_dropped"] > 0, \
    "streaming retention kept results — soak would accumulate them"
assert row["serving_soak_rotations"] >= 1, \
    "log cap never rotated — rotation path untested"
assert os.path.exists(sys.argv[2] + ".1"), "rotated mirror missing"
print(f"soak smoke: {row['serving_soak_completed']} completed / "
      f"{row['serving_soak_shed']} shed over {row['serving_soak_ticks']} "
      f"ticks, census ok ({row['serving_soak_census_sweeps']} sweeps, "
      f"worst bound {row['serving_soak_census_worst_frac']:.0%}), "
      f"rss {row['serving_soak_rss_mib_final']:.0f} MiB "
      f"({row['serving_soak_rss_verdict']}), "
      f"{row['serving_soak_rotations']} log rotation(s)")
PY
    JAX_PLATFORMS=cpu python scripts/telemetry_report.py \
        "$smoke/soak.jsonl" --json --require resource,census > /dev/null
    echo "soak smoke OK"
    exit 0
fi

if [[ "${1:-}" == "--gateway-smoke" ]]; then
    echo "== gateway smoke (SSE serve + mid-stream hangup -> cancel, ledger clean) =="
    smoke=$(mktemp -d)
    trap 'rm -rf "$smoke"' EXIT
    JAX_PLATFORMS=cpu python - "$smoke/gw.jsonl" <<'PY'
import os
import sys
import time

os.environ["PDT_BLOCKSAN"] = "1"
import jax
import jax.numpy as jnp
import numpy as np

from pytorch_distributed_tpu.fleet import FleetRouter
from pytorch_distributed_tpu.gateway import Gateway, generate, open_stream
from pytorch_distributed_tpu.models.transformer import (
    TransformerLM, tiny_config,
)
from pytorch_distributed_tpu.telemetry.reqtrace import ReqTracer
from pytorch_distributed_tpu.utils.profiling import MetricsLogger

cfg = tiny_config(attention="dense", max_seq_len=96)
params = TransformerLM(cfg).init(
    jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
)["params"]
mlog = MetricsLogger(sys.argv[1])
router = FleetRouter(
    cfg, params, n_replicas=2, n_slots=3, block_len=8, prefill_chunk=8,
    async_host=True, retain_results=False, metrics_log=mlog,
    reqtrace=ReqTracer(sink=mlog),
)
gw = Gateway(router, port=0, metrics_log=mlog)
gw.start()
base = f"http://127.0.0.1:{gw.port}"
rng = np.random.default_rng(0)
prompt = rng.integers(1, cfg.vocab_size, (9,)).astype(np.int32)
# request 1: a full SSE stream to completion over a real socket
out = generate(base, prompt, 6)
assert out["status"] == 200 and out["outcome"] == "complete", out
assert len(out["tokens"]) == 6, out
# request 2: hang up after the first token — the disconnect→cancel path
st = open_stream(base, prompt, 40)
next(st.events())
st.close()
deadline = time.time() + 30
while time.time() < deadline and gw.metrics()["gateway_cancels"] < 1:
    time.sleep(0.05)
assert gw.metrics()["gateway_cancels"] >= 1, gw.metrics()
gw.stop()
router.drain(max_steps=4000)
router.blocksan.assert_clean()
assert router.metrics()["cancelled"] >= 1, router.metrics()
router.log_summary()
mlog.close()
print("gateway serve: 1 stream completed, 1 hangup cancelled, "
      "ledger clean")
PY
    JAX_PLATFORMS=cpu python scripts/explain_request.py \
        "$smoke/gw.jsonl" --find cancelled --assert-complete \
        > "$smoke/cancel.txt"
    grep -q "terminal outcome: CANCELLED" "$smoke/cancel.txt" \
        || { echo "explain output missing the cancelled outcome"; exit 1; }
    JAX_PLATFORMS=cpu python scripts/telemetry_report.py \
        "$smoke/gw.jsonl" --json --require http > /dev/null
    # the two heavy gateway tests are @slow (fast tier sits ~60 s under
    # its cap); node-id selection ignores -m, so they run here instead
    JAX_PLATFORMS=cpu python -m pytest -q -p no:cacheprovider \
        -p no:xdist -p no:randomly \
        "tests/test_gateway.py::test_disconnect_storm_leaks_zero_blocks" \
        "tests/test_gateway.py::test_serve_lm_http_port_recipe"
    echo "gateway smoke OK"
    exit 0
fi

if [[ "${1:-}" == "--bench-regression" ]]; then
    echo "== bench regression (newest round vs previous, noise-banded) =="
    python scripts/bench_regression.py --auto --json
    # round 18: the bench numbers are only comparable if the sanitizer
    # really is detached when PDT_BLOCKSAN is unset
    JAX_PLATFORMS=cpu python scripts/bench_regression.py --blocksan-off
    exit 0
fi

if [[ "${1:-}" == "--obs-smoke" ]]; then
    echo "== observability smoke (hang -> anomaly; suspend -> dump; cost cards) =="
    smoke=$(mktemp -d)
    trap 'rm -rf "$smoke"' EXIT
    # CPU has no builtin roofline ceilings; pin synthetic ones so the
    # report's MFU/bound columns render (the numbers gate presence, not
    # magnitude)
    export PDT_PEAK_FLOPS=1e12 PDT_PEAK_GBS=100
    # run A: seeded hang at step 12 of 16 (--batch-size 1 -> 16 steps,
    # past the sentinel's warmup window) -> kind="anomaly"; fit-end cost
    # cards
    JAX_PLATFORMS=cpu \
        XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8" \
        PDT_FAULT_PLAN='{"faults":[{"site":"train.step","kind":"hang","at":12,"seconds":1.0}]}' \
        python recipes/lm_pretrain.py --tiny --epochs 1 --batch-size 1 \
        --save-dir "$smoke/lm" --metrics-out "$smoke/lm.jsonl" --cost-cards
    # run B: seeded suspend -> checkpoint-then-yield leaves the atomic
    # flight-recorder dump (exit 0 via the suspend path)
    JAX_PLATFORMS=cpu \
        XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8" \
        PDT_FAULT_PLAN='{"faults":[{"site":"train.step","kind":"suspend","at":4}]}' \
        python recipes/lm_pretrain.py --tiny --epochs 1 \
        --save-dir "$smoke/lm2" --metrics-out "$smoke/lm2.jsonl" || true
    python - "$smoke/lm2/flightrec_dump.json" <<'PY'
import json, sys
dump = json.load(open(sys.argv[1]))
assert dump["reason"] == "suspend" and dump["events"], dump.get("reason")
print(f"flight recorder: {len(dump['events'])} events, reason={dump['reason']}")
PY
    # serve cycle with cost cards
    JAX_PLATFORMS=cpu python recipes/serve_lm.py --tiny --requests 6 \
        --slots 4 --max-new 8 --metrics-out "$smoke/serve.jsonl" --cost-cards
    # the gate: roofline table + >=1 anomaly, from the JSONLs alone
    JAX_PLATFORMS=cpu python scripts/telemetry_report.py \
        "$smoke/lm.jsonl" "$smoke/serve.jsonl" --json --require cost,anomaly
    exit 0
fi

if [[ "${1:-}" == "--telemetry-smoke" ]]; then
    echo "== telemetry smoke (train + serve → JSONL → report) =="
    smoke=$(mktemp -d)
    trap 'rm -rf "$smoke"' EXIT
    # the tiny LM recipe needs the 8 virtual CPU devices its docstring
    # prescribes (dp2 × sp2 × tp1 by default)
    JAX_PLATFORMS=cpu \
        XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8" \
        python recipes/lm_pretrain.py --tiny --epochs 1 \
        --save-dir "$smoke/lm" --metrics-out "$smoke/lm.jsonl" \
        --flush-every 4 --trace-dir "$smoke/traces"
    JAX_PLATFORMS=cpu python recipes/serve_lm.py --tiny --requests 6 \
        --slots 4 --max-new 8 --metrics-out "$smoke/serve.jsonl"
    JAX_PLATFORMS=cpu python scripts/telemetry_report.py \
        "$smoke/lm.jsonl" "$smoke/serve.jsonl" --json \
        --require goodput,serving
    exit 0
fi

echo "== tier-1 tests =="
# the ROADMAP.md tier-1 verify command, verbatim
set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 870 env JAX_PLATFORMS=cpu \
    python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors \
    -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)"
exit $rc
