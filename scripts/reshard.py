"""Offline checkpoint repartitioning for a target mesh topology.

Rewrites a checkpoint's block table so a later restore on the target
mesh takes the zero-copy exact-block path on every region — the
assembly cost of a cross-topology restore, paid once offline instead of
inside every preemption window or per serving replica. Works on sharded
directories AND legacy single-file checkpoints; target shardings are
resolved from the partition-rule tables per leaf path (reshard/resolver
— no live model, no devices needed), so this runs on any host that can
see the files.

    # relayout a dp4xtp2 trainer checkpoint for a dp2xtp2 slice
    python scripts/reshard.py out/step-00000042.ckpt out/re22.ckpt \
        --mesh 2,1,2 --fsdp --verify

    # flatten for single-axis dp8 (tp rules vacuous at model=1)
    python scripts/reshard.py out/latest.ckpt out/re81.ckpt --mesh 8,1,1

``--check`` first proves the rule tables cover every shardable
parameter (analysis/partition_coverage.py) — the guarantee that
rule-derived targets are complete. Exit 0 on success; ``--json`` prints
machine-readable stats.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("src", help="source checkpoint (sharded dir or legacy "
                   "single file)")
    p.add_argument("dst", help="output checkpoint directory")
    p.add_argument("--mesh", required=True,
                   help="target data,seq,model axis sizes, e.g. 2,1,2")
    p.add_argument("--fsdp", action="store_true",
                   help="apply the ZeRO overlay: shard rule-unclaimed "
                        "big leaves over the data axis")
    p.add_argument("--rules", choices=["lm", "none"], default="lm",
                   help="partition-rule table: 'lm' = the transformer "
                        "TP tables (train/lm.py), 'none' = no rules "
                        "(image/ResNet checkpoints: FSDP overlay or "
                        "plain replication)")
    p.add_argument("--vocab-parallel", action="store_true",
                   help="include the vocab-parallel head/embedding rules")
    p.add_argument("--tp-size", type=int, default=None,
                   help="TP degree for conditional rules (default: the "
                        "target mesh's model axis size)")
    p.add_argument("--ep-size", type=int, default=0,
                   help="MoE expert-parallel degree (0 = no MoE rules)")
    p.add_argument("--force", action="store_true",
                   help="overwrite an existing checkpoint at dst")
    p.add_argument("--verify", action="store_true",
                   help="re-read both checkpoints and bit-compare every "
                        "leaf afterwards")
    p.add_argument("--check", action="store_true",
                   help="run the partition-coverage proof before "
                        "resharding")
    p.add_argument("--json", action="store_true",
                   help="print stats as one JSON object")
    args = p.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")

    from pytorch_distributed_tpu.parallel.mesh import MESH_AXES
    from pytorch_distributed_tpu.reshard import (
        assert_rules_cover,
        lm_rules,
        repartition,
    )

    sizes = [int(x) for x in args.mesh.split(",")]
    if len(sizes) != len(MESH_AXES):
        raise SystemExit(
            f"--mesh wants {len(MESH_AXES)} sizes ({','.join(MESH_AXES)}), "
            f"got {args.mesh!r}"
        )
    mesh_shape = dict(zip(MESH_AXES, sizes))

    if args.check:
        assert_rules_cover()
        print("partition-coverage: ok (every shardable param is "
              "rule-claimed)")

    if args.rules == "none":
        rules = ()
    else:
        import types

        tp = args.tp_size if args.tp_size is not None else mesh_shape[
            MESH_AXES[-1]
        ]
        # a duck config carrying exactly the fields the conditional rule
        # builders read — the CLI has no TransformerConfig to hand
        cfg = types.SimpleNamespace(
            model_axis=MESH_AXES[-1] if tp > 1 else None,
            tp_size=tp,
            vocab_parallel=args.vocab_parallel,
            n_experts=1 if args.ep_size > 1 else 0,
            expert_axis=MESH_AXES[0] if args.ep_size > 1 else None,
            ep_size=args.ep_size,
        )
        rules = lm_rules(cfg)

    t0 = time.perf_counter()
    stats = repartition(
        args.src, args.dst, mesh_shape,
        rules=rules, fsdp=args.fsdp, mesh_axes=list(MESH_AXES),
        overwrite=args.force, verify=args.verify,
    )
    wall = time.perf_counter() - t0

    out = {
        "reshard_src": args.src,
        "reshard_dst": args.dst,
        "reshard_mesh": args.mesh,
        "reshard_leaves": stats["leaves"],
        "reshard_blocks": stats["blocks"],
        "reshard_mb": round(stats["bytes"] / 2**20, 1),
        "reshard_s": round(wall, 2),
        "reshard_verified": bool(stats.get("verified", False)),
    }
    if args.json:
        print(json.dumps(out))
    else:
        print(
            f"resharded {out['reshard_leaves']} leaves / "
            f"{out['reshard_blocks']} blocks "
            f"({out['reshard_mb']} MB) for mesh [{args.mesh}] in "
            f"{out['reshard_s']} s"
            + (" — verified bit-equal" if out["reshard_verified"] else "")
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
