#!/usr/bin/env python
"""Kernel autotune sweep CLI (telemetry/autotune.py front-end).

Times every candidate (block_len × prefill_chunk × split_s) serving
config with the warm-decode-tick methodology of
``bench_serving.py --gather-ab``, joins each candidate with its decode
program's cost-card roofline class, and persists the winner keyed by
the autotune fingerprint — the registry fingerprint with the tuned
knobs normalized out. Any engine later constructed with
``autotune_dir=`` (or env ``PDT_AUTOTUNE_DIR``) pointing at ``--out-dir``
and matching the fingerprint loads the winner automatically.

Examples::

    # tiny CPU smoke: sweep two block lengths and the split knob
    python scripts/autotune.py --tiny --out-dir /tmp/tuned \
        --block-lens 8,16 --split-ss 1,2 --json

    # GPT-2 shape, fp8 pool, pallas gather (run on the TPU you serve on:
    # the fingerprint binds the file to that backend/device)
    python scripts/autotune.py --out-dir /tmp/tuned \
        --gather-impl pallas --kv-dtype fp8

HONESTY: the tuned file records the backend it was MEASURED on; a sweep
run on the CPU backend timed the Pallas interpreter and its winner is a
plumbing artifact, not a TPU performance claim (same rule as the
``gather_ab_backend`` bench rows).
"""

import argparse
import json
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def _ints(text):
    return tuple(int(x) for x in text.split(",") if x)


def _splits(text):
    # "1,2,auto" — 'auto' means split_s=None (the threshold policy)
    out = []
    for x in text.split(","):
        x = x.strip()
        if not x:
            continue
        out.append(None if x == "auto" else int(x))
    return tuple(out)


def main():
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--tiny", action="store_true",
                   help="tiny fp32 model (CPU smoke) instead of GPT-2")
    p.add_argument("--slots", type=int, default=4)
    p.add_argument("--ticks", type=int, default=8,
                   help="timed decode ticks per candidate (one extra "
                        "untimed tick warms each program)")
    p.add_argument("--prompt-len", type=int, default=32)
    p.add_argument("--block-lens", type=_ints, default=(8, 16),
                   metavar="N,N,...")
    p.add_argument("--prefill-chunks", type=_ints, default=(32,),
                   metavar="N,N,...")
    p.add_argument("--split-ss", type=_splits, default=(1, 2),
                   metavar="N|auto,...",
                   help="split-S candidates; 'auto' = the W/B threshold "
                        "policy")
    p.add_argument("--gather-impl", choices=("dense", "pallas"),
                   default="pallas")
    p.add_argument("--kv-dtype", choices=("int8", "fp8", "fp8_e5m2"),
                   default=None)
    p.add_argument("--out-dir", required=True,
                   help="directory the tuned JSON is written into "
                        "(autotune_<fingerprint>.json)")
    p.add_argument("--json", action="store_true",
                   help="print the tuned config as JSON")
    args = p.parse_args()

    import dataclasses

    import jax
    import jax.numpy as jnp

    from pytorch_distributed_tpu.models.transformer import (
        TransformerConfig,
        TransformerLM,
        tiny_config,
    )
    from pytorch_distributed_tpu.telemetry.autotune import sweep, tuned_path

    if args.tiny:
        # same shape as bench_serving._tiny_model so a sweep here feeds
        # the --gather-ab --tuned A/B (fingerprints must agree)
        cfg = tiny_config(attention="dense", max_seq_len=256,
                          dtype=jnp.float32)
    else:
        cfg = TransformerConfig(
            vocab_size=32000, num_layers=12, num_heads=12, embed_dim=768,
            max_seq_len=1024, dtype=jnp.bfloat16, attention="dense",
        )
    params = TransformerLM(cfg).init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]

    tuned = sweep(
        cfg, params, args.slots,
        block_lens=args.block_lens,
        prefill_chunks=args.prefill_chunks,
        split_ss=args.split_ss,
        kv_dtype=args.kv_dtype,
        gather_impl=args.gather_impl,
        prompt_len=args.prompt_len,
        ticks=args.ticks,
        out_dir=args.out_dir,
    )
    path = tuned_path(args.out_dir, tuned.fingerprint)
    if args.json:
        print(json.dumps(dataclasses.asdict(tuned), indent=2))
    else:
        print(f"winner: block_len={tuned.block_len} "
              f"prefill_chunk={tuned.prefill_chunk} "
              f"split_s={tuned.split_s} "
              f"({tuned.decode_tok_s} tok/s, bound={tuned.decode_bound}, "
              f"backend={tuned.backend}, "
              f"{len(tuned.candidates)} candidates)")
        print(f"saved: {path}")


if __name__ == "__main__":
    main()
