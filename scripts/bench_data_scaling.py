"""Host input-pipeline WORKER scaling + the GIL evidence (VERDICT r3 #7).

The r3 gap: every data-pipeline number was measured with one worker on
one core, leaving "threads + GIL-releasing decode scale like the
reference's 8 NUMA processes" as an untested claim. This host has
exactly ONE physical core (`nproc` = 1), so a worker sweep here CANNOT
show real multi-core scaling — instead this script measures the two
things one core CAN prove, and states the limit honestly:

1. **Worker sweep** (JPEG and raw paths, num_workers ∈ {0,1,2,4,8}):
   on one core the expectation is FLAT throughput with no
   thread-overhead collapse — threads must not cost, even when they
   cannot pay. A drop at higher worker counts would be a real queue/
   lock bottleneck; flat curves mean the machinery adds ~zero overhead.
2. **GIL-release proof** per pipeline stage: a counter thread spins in
   pure Python while the stage runs in another thread. A stage that
   HOLDS the GIL starves the counter to ~0 during its C call; a stage
   that releases it lets the counter timeshare (~half rate on one
   core). Measured for PIL JPEG decode, PIL resize, the TPRC C++ batch
   read, and (as a deliberate negative control) ``ndarray.tolist``,
   which builds PyObjects under the lock.

Together: the worker machinery is overhead-free and the heavy stages
(decode, resize, record IO) demonstrably release the GIL — the two
preconditions for thread scaling on a real multi-core host. The
remaining per-core number (bench.py: ~9.8k img/s/core raw) says a
v5e-8 host feed (~24k img/s) needs ~3 cores of an 8-core host.

Usage: python scripts/bench_data_scaling.py [--n 1024]
Prints one JSON line per measurement.
"""

from __future__ import annotations

import io
import json
import os
import sys
import tempfile
import threading
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def synth_jpegs(n: int, size: int = 256):
    from PIL import Image

    rng = np.random.default_rng(0)
    for i in range(n):
        base = rng.integers(0, 255, (8, 8, 3)).astype(np.uint8)
        img = Image.fromarray(base).resize((size, size), Image.BILINEAR)
        buf = io.BytesIO()
        img.save(buf, "JPEG", quality=90)
        yield buf.getvalue(), i % 1000


def build_splits(n: int):
    from pytorch_distributed_tpu.data.imagenet import write_imagenet_split
    from pytorch_distributed_tpu.data.raw import write_imagenet_raw_split

    cache = os.path.join(tempfile.gettempdir(), f"pdt_scaling_{n}")
    jpeg = os.path.join(cache, "train.tprc")
    raw = os.path.join(cache, "train.rawtprc")
    if not os.path.exists(jpeg):
        os.makedirs(cache, exist_ok=True)
        write_imagenet_split(jpeg, synth_jpegs(n))
    if not os.path.exists(raw):
        rng = np.random.default_rng(1)
        write_imagenet_raw_split(
            raw,
            ((rng.integers(0, 255, (256, 256, 3)).astype(np.uint8), i % 1000)
             for i in range(n)),
        )
    return cache


def sweep_workers(cache: str) -> None:
    from pytorch_distributed_tpu.data.imagenet import ImageNet
    from pytorch_distributed_tpu.data.loader import (
        DataLoader,
        measure_throughput,
    )
    from pytorch_distributed_tpu.data.raw import RawImageNet

    for mode, ds_fn in (
        ("jpeg", lambda: ImageNet("train", data_dir=cache)),
        ("raw", lambda: RawImageNet("train", data_dir=cache, aug="crop")),
    ):
        base = None
        for workers in (0, 1, 2, 4, 8):
            loader = DataLoader(ds_fn(), batch_size=128,
                                num_workers=workers, prefetch=4)
            img_s = measure_throughput(loader, epochs=2)
            if workers <= 1 and (base is None or img_s > base):
                base = img_s
            print(json.dumps({
                "path": mode, "num_workers": workers,
                "img_s": round(img_s, 1),
                "vs_1worker": round(img_s / base, 3) if base else None,
                "host_cores": os.cpu_count(),
            }))


def gil_release_probe() -> None:
    """Counter-starvation test: counts/sec of a pure-Python thread while
    a candidate stage runs. ratio ≈ 0 → stage holds the GIL; ratio
    clearly > 0.3 → stage releases it (timesharing one core; the
    GIL-holding control measures ~0.14 — switch-interval leakage)."""
    from PIL import Image

    rng = np.random.default_rng(2)
    big = Image.fromarray(
        rng.integers(0, 255, (64, 64, 3)).astype(np.uint8)
    ).resize((4096, 4096), Image.BILINEAR)
    buf = io.BytesIO()
    big.save(buf, "JPEG", quality=95)
    jpeg_bytes = buf.getvalue()

    from pytorch_distributed_tpu.data.raw import (
        RawImageNet,
        write_imagenet_raw_split,
    )

    cache = os.path.join(tempfile.gettempdir(), "pdt_gil_probe")
    raw = os.path.join(cache, "train.rawtprc")
    if not os.path.exists(raw):
        os.makedirs(cache, exist_ok=True)
        write_imagenet_raw_split(
            raw,
            ((rng.integers(0, 255, (256, 256, 3)).astype(np.uint8), i)
             for i in range(512)),
        )
    ds = RawImageNet("train", data_dir=cache, aug="crop")
    reader = ds.reader  # TPRC native batch reader

    small = rng.standard_normal((2048, 2048)).astype(np.float32)
    stages = {
        "pil_jpeg_decode": lambda: Image.open(
            io.BytesIO(jpeg_bytes)).convert("RGB").load(),
        "pil_resize": lambda: big.resize((2048, 2048), Image.BILINEAR),
        "tprc_batch_read": lambda: reader.read_batch(list(range(256))),
        # CONTROL that genuinely HOLDS the GIL: ndarray.tolist builds
        # millions of PyObjects under the lock (numpy ufuncs like np.exp
        # RELEASE it, so they are not a valid negative control)
        "ndarray_tolist_CONTROL": lambda: small.tolist(),
    }

    def counter_rate(during, runs=5):
        stop = [False]
        count = [0]
        go = threading.Event()

        def spin():
            go.wait()  # count only inside the timed window
            c = 0
            while not stop[0]:
                c += 1
            count[0] = c

        t = threading.Thread(target=spin)
        t.start()
        time.sleep(0.05)  # thread up and parked on the event
        t0 = time.perf_counter()
        go.set()
        for _ in range(runs):
            during()
        dt = time.perf_counter() - t0
        stop[0] = True
        t.join()
        return count[0] / dt, dt / runs

    # baseline: the SAME tight counter loop with no competing work (the
    # loop body must match the probe's exactly for rates to compare)
    base_rate, _ = counter_rate(lambda: time.sleep(0.1), runs=5)

    for name, fn in stages.items():
        fn()  # warm (file cache, PIL lazy init)
        rate, stage_s = counter_rate(fn)
        print(json.dumps({
            "stage": name,
            "stage_ms": round(stage_s * 1e3, 1),
            "counter_ratio_vs_idle": round(rate / base_rate, 3),
            "gil": "released" if rate / base_rate > 0.3 else "HELD",
        }))


def main() -> None:
    n = 1024
    if "--n" in sys.argv:
        n = int(sys.argv[sys.argv.index("--n") + 1])
    cache = build_splits(n)
    sweep_workers(cache)
    gil_release_probe()


if __name__ == "__main__":
    main()
