"""ResNet wall quantification + the int8-trunk storage experiment
(VERDICT r3 next #3).

Three measurements on the real chip, one JSON line each:

1. ``hbm_ceiling_gb_s`` — MEASURED streaming bandwidth: a triad-style
   ``y = x * a + b`` over a 1 GiB bf16 array (2 bytes moved per stored
   byte: one read + one write), timed by the chained-slope method. This
   replaces the datasheet 819 GB/s / estimated ~690 GB/s numbers with
   what THIS chip actually streams.
2. ``resnet_achieved_gb_s`` — the bf16 bs128 fused train step's analytic
   minimum HBM traffic divided by its measured step time. The byte count
   enumerates the tensors the compiled program MUST materialize
   (per-conv inputs/outputs fwd, their re-reads + grad writes bwd,
   params+grads+momentum), assuming perfect elementwise/BN fusion into
   conv epilogues — i.e. it UNDERCOUNTS real traffic, so the reported
   roofline fraction is a LOWER bound.
3. ``int8_trunk_img_s`` — one storage-level lever, measured: residual
   trunk stored int8 between blocks (models/resnet.py ``int8_trunk``,
   STE grads, opt-in/non-parity). Reported win or lose.

Usage: python scripts/exp_resnet_roofline.py
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "scripts"))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def measure_hbm_ceiling() -> float:
    """Streaming GB/s of y = x*a+b over 512M bf16 elements (1 GiB)."""
    from bench_attention import difftime

    n = 512 * 1024 * 1024
    x = jnp.ones((n,), jnp.bfloat16)

    @jax.jit
    def chained(k):
        def body(i, carry):
            return carry * jnp.bfloat16(0.999) + jnp.bfloat16(1e-6)

        y = lax.fori_loop(0, k, body, x)
        return jnp.sum(y[:1].astype(jnp.float32))

    dt = difftime(chained, k1=5, k2=55)  # seconds per iteration
    bytes_moved = 2 * n * 2  # read + write, 2 B/elt
    return bytes_moved / dt / 1e9


def resnet50_min_traffic_bytes(bs: int = 128) -> int:
    """Analytic minimum HBM bytes of one fused-bottleneck bf16 train step.

    Counts, in bf16 (2 B) unless noted:
    - forward: every conv's input read + output write (convs cannot fuse
      into each other; BN/relu/residual ride epilogues for free in the
      fused-block design);
    - backward: each saved activation read once, each activation grad
      written+read once along the chain (remat off — the bench config);
    - params: fp32 read (fwd) + grad write + momentum read/write + param
      write (SGD, 4 B each).
    Stats/LSE-style small vectors are ignored (<1% of the total).
    """
    # (H, W, C_in, C_out, convs per block): ResNet-50 stages at 224 input
    stem = (224 * 224 * 3, 112 * 112 * 64)  # 7x7/2 conv in/out elements
    pool = (112 * 112 * 64, 56 * 56 * 64)
    stages = [  # (n_blocks, H, W, f, expansion 4)
        (3, 56, 64), (4, 28, 128), (6, 14, 256), (3, 7, 512),
    ]
    elems = stem[0] + stem[1] + pool[0] + pool[1]  # stem + maxpool traffic
    for n_blocks, hw, f in stages:
        for b in range(n_blocks):
            first = b == 0
            # block input: stage1 block0 reads the 56x56x64 maxpool output
            # (stride 1); later stages' block0 reads the previous stage's
            # 2hw x 2hw x 2f output (stride 2); non-first blocks read
            # hw x hw x 4f.
            hw_in = hw if (not first or f == 64) else hw * 2
            cin_real = (4 * f) if not first else (64 if f == 64 else 2 * f)
            # conv1 1x1: [hw_in^2, cin] -> [hw_in^2, f]
            # conv2 3x3/s: -> [hw^2, f]; conv3 1x1: -> [hw^2, 4f]
            # downsample (first block): block input -> [hw^2, 4f]
            c1_in = hw_in * hw_in * cin_real
            c1_out = hw_in * hw_in * f
            c2_out = hw * hw * f
            c3_out = hw * hw * 4 * f
            fwd = c1_in + c1_out + (c1_out + c2_out) + (c2_out + c3_out)
            if first:
                fwd += c1_in + c3_out  # downsample read + write
            # bwd: read saved (c1_in, c1_out, c2_out) + grad chain
            # write+read per conv boundary + residual grad
            bwd = (c1_in + c1_out + c2_out) + 2 * (c1_out + c2_out + c3_out)
            if first:
                bwd += c1_in + c3_out
            elems += fwd + bwd
    act_bytes = elems * bs * 2  # bf16
    params = 25_557_032
    param_bytes = params * 4 * 5  # read + grad w + mom r/w + param w, fp32
    return act_bytes + param_bytes


def main() -> None:
    import bench

    ceiling = measure_hbm_ceiling()
    print(json.dumps({"hbm_ceiling_gb_s": round(ceiling, 1),
                      "method": "bf16 triad 1GiB, chained-slope"}))

    bs = int(os.environ.get("BENCH_BS", "128"))
    img_s, step_s, _ = bench.run(bs, tiny=False, fused=True,
                                 measure_duty=False)
    traffic = resnet50_min_traffic_bytes(bs)
    achieved = traffic / step_s / 1e9
    print(json.dumps({
        "resnet_bf16_img_s": round(img_s, 1),
        "step_ms": round(step_s * 1e3, 2),
        "analytic_min_traffic_gb": round(traffic / 1e9, 2),
        "resnet_achieved_gb_s": round(achieved, 1),
        "roofline_fraction_lower_bound": round(achieved / ceiling, 3),
    }))

    img_s8, step_s8, _ = bench.run(bs, tiny=False, fused=True,
                                   int8_trunk=True, measure_duty=False)
    print(json.dumps({
        "int8_trunk_img_s": round(img_s8, 1),
        "int8_trunk_step_ms": round(step_s8 * 1e3, 2),
        "int8_trunk_speedup": round(img_s8 / img_s, 4),
    }))


if __name__ == "__main__":
    main()
