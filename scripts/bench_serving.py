"""Ragged-serving throughput (VERDICT r3 #10 done-condition: measured
tok/s at batch 32).

GPT-2-small-shaped decode config, 32 requests with random prompt lengths
in [16, 256] right-padded to 256, greedy. Measures:

- ragged prefill latency (one batched causal forward, all 32 prompts);
- steady-state DECODE throughput (tokens/s across the 32 slots) via the
  chained generate_ragged scan — timing per PERF_NOTES.md (scalar-fetch
  sync, round-trip subtracted).

Usage: python scripts/bench_serving.py [--slots 32]
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax
import jax.numpy as jnp
import numpy as np

from bench import measure_roundtrip_s  # noqa: E402  (scripts on path via cwd)


def measure(slots: int = 32, max_new: int = 64) -> dict:
    from pytorch_distributed_tpu.models.generate import (
        generate_ragged,
        ragged_prefill,
    )
    from pytorch_distributed_tpu.models.transformer import (
        TransformerConfig,
        TransformerLM,
    )
    cfg = TransformerConfig(
        vocab_size=32000, num_layers=12, num_heads=12, embed_dim=768,
        max_seq_len=1024, dtype=jnp.bfloat16, attention="dense",
    )
    params = TransformerLM(cfg).init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]

    rng = np.random.default_rng(0)
    lengths = rng.integers(16, 257, slots).astype(np.int32)
    prompts = np.zeros((slots, 256), np.int32)
    for i, l in enumerate(lengths):
        prompts[i, :l] = rng.integers(1, cfg.vocab_size, l)
    prompts_j = jnp.asarray(prompts)
    lengths_j = jnp.asarray(lengths)

    # prefill latency (compile, then time the steady call)
    pf = jax.jit(lambda p, pr, ln: ragged_prefill(cfg, p, pr, ln))
    cache, last = pf(params, prompts_j, lengths_j)
    float(jnp.sum(last[:, :1]))
    t0 = time.perf_counter()
    cache, last = pf(params, prompts_j, lengths_j)
    float(jnp.sum(last[:, :1]))
    prefill_s = max(
        time.perf_counter() - t0 - measure_roundtrip_s(), 1e-6
    )

    # decode throughput: the full ragged generate (prefill + max_new
    # decode steps); subtract the measured prefill to isolate decode
    out = generate_ragged(cfg, params, prompts_j, lengths_j,
                          jax.random.key(1), max_new_tokens=max_new)
    int(np.asarray(out)[0, 0])  # compile + drain
    t0 = time.perf_counter()
    out = generate_ragged(cfg, params, prompts_j, lengths_j,
                          jax.random.key(1), max_new_tokens=max_new)
    int(np.asarray(out)[0, 0])
    total_s = max(time.perf_counter() - t0 - measure_roundtrip_s(), 1e-6)
    decode_s = max(total_s - prefill_s, 1e-6)

    return {
        "serving_slots": slots,
        "serving_prompt_lens": f"{int(lengths.min())}-{int(lengths.max())}",
        "serving_max_new_tokens": max_new,
        "serving_prefill_ms": round(prefill_s * 1e3, 1),
        "serving_prefill_prompt_tok_s": round(
            float(lengths.sum()) / prefill_s
        ),
        "serving_decode_tok_s": round(slots * max_new / decode_s),
        "serving_decode_ms_per_token": round(decode_s / max_new * 1e3, 2),
        "device": str(jax.devices()[0]),
    }


def main() -> None:
    slots = 32
    if "--slots" in sys.argv:
        slots = int(sys.argv[sys.argv.index("--slots") + 1])
    print(json.dumps(measure(slots)))


if __name__ == "__main__":
    main()
