"""Ragged-serving throughput (VERDICT r3 #10 done-condition: measured
tok/s at batch 32).

GPT-2-small-shaped decode config, 32 requests with random prompt lengths
in [16, 256] right-padded to 256, greedy. Measures:

- ragged prefill latency (one batched causal forward, all 32 prompts);
- steady-state DECODE throughput (tokens/s across the 32 slots) via the
  chained generate_ragged scan — timing per PERF_NOTES.md (scalar-fetch
  sync, round-trip subtracted).

Usage: python scripts/bench_serving.py [--slots 32]
       python scripts/bench_serving.py --paged-latency   # TTFT/token p50/p95
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax
import jax.numpy as jnp
import numpy as np

from bench import measure_roundtrip_s  # noqa: E402  (scripts on path via cwd)


def _gpt2_model(max_seq_len=1024, dtype=None, **over):
    """One GPT-2-small-shaped serving config + init — shared by every
    measurement here so the stall numbers can never drift to a different
    model than the tick rate they are combined with."""
    from pytorch_distributed_tpu.models.transformer import (
        TransformerConfig,
        TransformerLM,
    )

    cfg = TransformerConfig(
        vocab_size=32000, num_layers=12, num_heads=12, embed_dim=768,
        max_seq_len=max_seq_len,
        dtype=dtype if dtype is not None else jnp.bfloat16,
        attention="dense", **over,
    )
    params = TransformerLM(cfg).init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return cfg, params


def measure(slots: int = 32, max_new: int = 64) -> dict:
    from pytorch_distributed_tpu.models.generate import (
        generate_ragged,
        ragged_prefill,
    )

    cfg, params = _gpt2_model()

    rng = np.random.default_rng(0)
    lengths = rng.integers(16, 257, slots).astype(np.int32)
    prompts = np.zeros((slots, 256), np.int32)
    for i, l in enumerate(lengths):
        prompts[i, :l] = rng.integers(1, cfg.vocab_size, l)
    prompts_j = jnp.asarray(prompts)
    lengths_j = jnp.asarray(lengths)

    # prefill latency (compile, then time the steady call)
    pf = jax.jit(lambda p, pr, ln: ragged_prefill(cfg, p, pr, ln))
    cache, last = pf(params, prompts_j, lengths_j)
    float(jnp.sum(last[:, :1]))
    t0 = time.perf_counter()
    cache, last = pf(params, prompts_j, lengths_j)
    float(jnp.sum(last[:, :1]))
    prefill_s = max(
        time.perf_counter() - t0 - measure_roundtrip_s(), 1e-6
    )

    # decode throughput: the full ragged generate (prefill + max_new
    # decode steps); subtract the measured prefill to isolate decode.
    # THREE runs, quoted median + min-max spread: serving decode through
    # the tunnel has shown a ±14% run-to-run band (VERDICT r4 weak #6) —
    # a single sample measures the tunnel's weather, not the decoder.
    out = generate_ragged(cfg, params, prompts_j, lengths_j,
                          jax.random.key(1), max_new_tokens=max_new)
    int(np.asarray(out)[0, 0])  # compile + drain
    decode_rates = []
    for _ in range(3):
        t0 = time.perf_counter()
        out = generate_ragged(cfg, params, prompts_j, lengths_j,
                              jax.random.key(1), max_new_tokens=max_new)
        int(np.asarray(out)[0, 0])
        total_s = max(
            time.perf_counter() - t0 - measure_roundtrip_s(), 1e-6
        )
        decode_s = max(total_s - prefill_s, 1e-6)
        decode_rates.append(slots * max_new / decode_s)
    decode_tok_s = float(np.median(decode_rates))

    return {
        "serving_slots": slots,
        "serving_prompt_lens": f"{int(lengths.min())}-{int(lengths.max())}",
        "serving_max_new_tokens": max_new,
        "serving_prefill_ms": round(prefill_s * 1e3, 1),
        "serving_prefill_prompt_tok_s": round(
            float(lengths.sum()) / prefill_s
        ),
        "serving_decode_tok_s": round(decode_tok_s),
        "serving_decode_tok_s_min": round(min(decode_rates)),
        "serving_decode_tok_s_max": round(max(decode_rates)),
        # per-TICK latency (all slots advance one token per tick)
        "serving_decode_ms_per_token": round(
            slots * 1e3 / decode_tok_s, 2
        ),
        "device": str(jax.devices()[0]),
    }


def measure_admission_stall(slots: int = 32, n: int = 10,
                            tick_ms: float | None = None) -> dict:
    """Per-admission decode stall of the ContinuousBatcher (VERDICT r4
    next #7).

    ``submit`` runs a full batch-1 prefill + row insert while every
    active decode lane waits — that wall time IS the stall each
    admission imposes on the other ``slots-1`` requests. Measured as
    DEVICE program time (chained dispatch, one scalar sync, round-trip
    subtracted — the tunnel's ~95 ms host hop would otherwise swamp the
    ~17 ms program; on a real TPU VM the host hop is microseconds).
    Reported per prefill bucket, plus the closed-form steady-state
    throughput under Poisson arrivals at the equilibrium rate
    (every completed request replaced: λ_eq = slots / T_request), which
    is what a Poisson trace converges to when the system is kept full.
    """
    from pytorch_distributed_tpu.models.generate import ContinuousBatcher

    cfg, params = _gpt2_model()
    # the DENSE layout's stall — the number the paged engine exists to
    # beat; measure_paged_admission reports the paged counterpart
    b = ContinuousBatcher(cfg, params, n_slots=slots, prefill_bucket=128,
                          cache_layout="dense")

    rng = np.random.default_rng(0)
    out: dict = {"serving_stall_slots": slots}

    # per-bucket SUBMIT program time — prefill + in-program row insert
    # (one donated program; the standalone insert measured ~8 ms of
    # full-cache copy, which dies when the write shares the producer's
    # program). This wall time is exactly the stall every active decode
    # lane sees per admission.
    stall_by_bucket = {}
    slot = jnp.asarray(0)
    for width in (128, 256):
        prompt = jnp.asarray(
            rng.integers(1, cfg.vocab_size, (1, width)).astype(np.int32)
        )
        length = jnp.asarray([width - 7], jnp.int32)
        for _ in range(3):  # compile + settle donation/layout
            b.cache, b.logits = b._submit_one(
                params, prompt, length, b.cache, b.logits, slot
            )
        float(jnp.sum(b.logits[:1, :1]))
        t0 = time.perf_counter()
        for _ in range(n):
            b.cache, b.logits = b._submit_one(
                params, prompt, length, b.cache, b.logits, slot
            )
        float(jnp.sum(b.logits[:1, :1]))
        dt = time.perf_counter() - t0
        stall_by_bucket[width] = (
            max(dt - measure_roundtrip_s(), dt / 2) / n * 1e3
        )
        out[f"serving_admission_stall_ms_b{width}"] = round(
            stall_by_bucket[width], 2
        )

    # decode tick time from the spread-quoted headline measurement
    # (pass tick_ms when the caller already ran measure() — bench.py)
    if tick_ms is None:
        tick_ms = measure(slots=slots, max_new=64)[
            "serving_decode_ms_per_token"
        ]
    out["serving_decode_tick_ms"] = tick_ms

    # Steady state under Poisson arrivals at the equilibrium rate (system
    # kept full): each request = one admission stall + max_new ticks
    # shared with the other slots. Effective tok/s =
    # slots*max_new / (slots*stall + max_new*tick).
    stall = stall_by_bucket[256]  # median prompt ~200 tokens → 256 bucket
    for max_new in (64, 256):
        eff = slots * max_new / (
            slots * stall + max_new * tick_ms
        ) * 1e3
        out[f"serving_equilibrium_tok_s_new{max_new}"] = round(eff)
        out[f"serving_admission_overhead_frac_new{max_new}"] = round(
            slots * stall / (slots * stall + max_new * tick_ms), 3
        )
    return out


def measure_paged_admission(slots: int = 32, n: int = 10,
                            tick_ms: float | None = None) -> dict:
    """Per-admission cost of the PAGED engine (the round-6 tentpole) and
    the equilibrium short-output throughput model it implies — the
    admission-heavy workload where the dense layout paid its ~30% tax.

    An admission here is ``ContinuousBatcher.submit`` on the default
    paged layout: block-chain allocation (host) + one chunk program per
    prompt chunk writing into FRESH blocks — O(prompt), never touching
    resident KV. Timed as chained dispatch over ``n`` admissions into
    distinct slots with ONE sync, round-trip subtracted (same method as
    the dense stall). Reported per prefill-chunk bucket alongside the
    same closed-form equilibrium throughput the dense measurement uses,
    so ``serving_paged_admission_overhead_frac_new64`` is directly
    comparable with ``serving_admission_overhead_frac_new64``.
    """
    from pytorch_distributed_tpu.models.generate import ContinuousBatcher

    cfg, params = _gpt2_model()
    b = ContinuousBatcher(cfg, params, n_slots=slots, prefill_bucket=128)
    rng = np.random.default_rng(0)
    out: dict = {
        "serving_paged_block_len": b.engine.block_len,
        "serving_paged_chunk": b.engine.chunk,
    }

    stall_by_bucket = {}
    for width in (128, 256):
        prompt = rng.integers(
            1, cfg.vocab_size, (width - 7,)
        ).astype(np.int32)
        for _ in range(2):  # compile + settle donation
            b.submit(prompt, 1)
            b.step()  # budget 1: retires, frees the slot and its blocks
        jax.block_until_ready(b.logits)
        t0 = time.perf_counter()
        for _ in range(n):
            b.submit(prompt, 1)
        jax.block_until_ready(b.logits)
        dt = time.perf_counter() - t0
        while any(b.remaining > 0):
            b.step()
        stall_by_bucket[width] = (
            max(dt - measure_roundtrip_s(), dt / 2) / n * 1e3
        )
        out[f"serving_paged_admission_stall_ms_b{width}"] = round(
            stall_by_bucket[width], 2
        )

    if tick_ms is None:
        tick_ms = measure(slots=slots, max_new=64)[
            "serving_decode_ms_per_token"
        ]
    stall = stall_by_bucket[256]
    for max_new in (64, 256):
        eff = slots * max_new / (slots * stall + max_new * tick_ms) * 1e3
        out[f"serving_paged_equilibrium_tok_s_new{max_new}"] = round(eff)
        out[f"serving_paged_admission_overhead_frac_new{max_new}"] = round(
            slots * stall / (slots * stall + max_new * tick_ms), 3
        )
    return out


def measure_paged_latency(slots: int = 16, requests: int = 48,
                          max_new: int = 32) -> dict:
    """End-to-end latency percentiles of the paged scheduler under a
    queued multi-tenant workload (ISSUE 4: the one metric a
    vLLM/Orca-style continuous batcher exists to control, previously
    unreported). Drives ``serving.Scheduler`` with ``requests`` random
    prompts (3x oversubscribed vs ``slots``), exact host-side TTFT /
    per-output-token / queue-wait series from the scheduler's own
    timestamps — no extra syncs beyond the token fetch every tick
    already pays."""
    from pytorch_distributed_tpu.serving import Scheduler

    cfg, params = _gpt2_model()
    rng = np.random.default_rng(0)
    sched = Scheduler(cfg, params, n_slots=slots, prefill_chunk=64,
                      admit_per_step=4)
    lens = rng.integers(16, 257, requests)
    for l in lens:
        sched.submit(
            rng.integers(1, cfg.vocab_size, size=int(l)).astype(np.int32),
            max_new,
        )
    sched.drain()
    m = sched.metrics()
    out = {
        "serving_paged_lat_slots": slots,
        "serving_paged_lat_requests": requests,
        "serving_paged_lat_max_new": max_new,
        "serving_paged_tokens_per_s": round(m["tokens_per_s"], 1),
    }
    for name in ("ttft", "token_lat", "queue_wait"):
        for q in ("p50", "p95"):
            key = f"{name}_{q}_s"
            if key in m:
                out[f"serving_paged_{name}_{q}_ms"] = round(
                    m[key] * 1e3, 2
                )
    return out


def measure_tp_virtual(slots: int = 8, tp: int = 2) -> dict:
    """TP batcher decode rate on the VIRTUAL CPU mesh — a functionality
    row, not a performance claim (tp>1 needs more chips than this
    environment has; re-measure on real multi-chip hardware). Parity is
    tested in tests/test_serving_tp.py."""
    import dataclasses

    from pytorch_distributed_tpu.models.generate import generate_ragged_tp
    from pytorch_distributed_tpu.parallel import make_mesh

    if len(jax.devices()) < tp:
        return {"serving_tp_error": f"needs {tp} devices"}
    # ONE init with the replicated twin (a TP config cannot init outside
    # shard_map — tp_reduce's psum has no axis); the TP cfg is a replace
    rep, params = _gpt2_model(max_seq_len=512, dtype=jnp.float32)
    cfg = dataclasses.replace(rep, model_axis="model", tp_size=tp)
    mesh = make_mesh(jax.devices()[:tp], data_parallel=1, seq_parallel=1,
                     model_parallel=tp)
    rng = np.random.default_rng(0)
    lengths = rng.integers(16, 129, slots).astype(np.int32)
    prompts = np.zeros((slots, 128), np.int32)
    for i, l in enumerate(lengths):
        prompts[i, :l] = rng.integers(1, cfg.vocab_size, l)
    args = (jnp.asarray(prompts), jnp.asarray(lengths),
            jax.random.key(1))
    out = generate_ragged_tp(mesh, cfg, params, *args, max_new_tokens=16)
    int(np.asarray(out)[0, 0])
    t0 = time.perf_counter()
    out = generate_ragged_tp(mesh, cfg, params, *args, max_new_tokens=16)
    int(np.asarray(out)[0, 0])
    dt = time.perf_counter() - t0
    return {
        "serving_tp_virtual_tok_s": round(slots * 16 / dt),
        "serving_tp_degree": tp,
        "serving_tp_note": "virtual CPU mesh: functionality, not perf",
    }


def main() -> None:
    slots = 32
    if "--slots" in sys.argv:
        slots = int(sys.argv[sys.argv.index("--slots") + 1])
    if "--stall" in sys.argv:
        print(json.dumps(measure_admission_stall(slots)))
        return
    if "--paged-stall" in sys.argv:
        print(json.dumps(measure_paged_admission(slots)))
        return
    if "--paged-latency" in sys.argv:
        print(json.dumps(measure_paged_latency()))
        return
    if "--tp-virtual" in sys.argv:
        print(json.dumps(measure_tp_virtual()))
        return
    print(json.dumps(measure(slots)))


if __name__ == "__main__":
    main()
