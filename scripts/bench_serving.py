"""Ragged-serving throughput (VERDICT r3 #10 done-condition: measured
tok/s at batch 32).

GPT-2-small-shaped decode config, 32 requests with random prompt lengths
in [16, 256] right-padded to 256, greedy. Measures:

- ragged prefill latency (one batched causal forward, all 32 prompts);
- steady-state DECODE throughput (tokens/s across the 32 slots) via the
  chained generate_ragged scan — timing per PERF_NOTES.md (scalar-fetch
  sync, round-trip subtracted).

Usage: python scripts/bench_serving.py [--slots 32]
       python scripts/bench_serving.py --paged-latency   # TTFT/token p50/p95
       python scripts/bench_serving.py --paged-latency --trace T.jsonl
       python scripts/bench_serving.py --gen-trace T.jsonl [--trace-seed 0
           --trace-duration 240 --trace-base-rate 0.32 --trace-burst-mult 4
           --trace-prompt-median 24 --trace-prompt-max 96
           --trace-max-new-median 12 --trace-prefill-heavy]
       python scripts/bench_serving.py --fleet [--trace T.jsonl]   # 1r vs 2r
       python scripts/bench_serving.py --disagg [--trace T.jsonl]  # colo vs PD
       python scripts/bench_serving.py --wall-clock [--trace T.jsonl
           --wc-replicas 2 --wc-slots 4 --wc-out overlap.jsonl
           --wc-extra 4 --wc-reps 3]  # round 15; round 16 adds the
                          # sync-vs-async A/B (serving_wallclock_async_*),
                          # extra fleet-size points (--wc-extra N,M), and
                          # median-of-reps quoting (--wc-reps)
       python scripts/bench_serving.py --gather-ab [--tiny --ab-slots 8
           --ab-ticks 32 --ab-prompt-len 64]  # pallas-vs-dense + int8 capacity
       python scripts/bench_serving.py --pressure [--pressure-sessions 100000
           --pressure-blocks 13 --pressure-duration 90]  # preempt vs shed-only
       python scripts/bench_serving.py --soak [--soak-requests 100000
           --soak-log soak.jsonl --soak-slots 8 --soak-replicas 2]
           # round 21 scale observatory: stream >=100k unique-session
           # requests, census + RSS/host-wall growth fits (serving_soak_*)
       python scripts/bench_serving.py --http [--http-requests 48
           --http-replicas 2 --http-disconnect-every 6 --http-out h.jsonl]
           # round 22 front door: real sockets against gateway.Gateway —
           # over-the-wire TTFT, SSE gap p95, 429 rate at the door, and
           # cancel-to-block-free latency (serving_http_*)

Round 15 (overlap profiler): ``--wall-clock`` is the ROADMAP-item-3
fleet bench — ONE trace served saturated (no nominal tick) by 1 replica
vs N with the dispatch ledger armed, reporting aggregate tok/s both
sides, per-replica device-busy fraction, and the bubble-cause histogram
that must account for >=90% of the measured 1→N efficiency gap
(``serving_wallclock_*``; backend-marked, CPU magnitudes not
regression-gated). ``--wc-out`` keeps the run's span+overlap JSONL for
``telemetry_report.py --require overlap`` / ``explain_request.py``.

Round 13 (pressure tier): ``--pressure`` replays one over-committed
bursty trace (default 100k session ids on a pool holding ~3 chains per
replica) through a shed-only fleet vs the same fleet with host offload
+ the SLO gate's preempt rung, and reports within-SLO goodput, shed
rates, preempt/restore counts, and swap p95 (``serving_pressure_*``).

Round 10 (fleet/): ``--gen-trace`` emits the reusable seeded
bursty/heavy-tail JSONL trace; ``--fleet`` replays ONE trace through a
1-replica and a 2-replica router at the SAME offered per-tick load and
reports goodput — completed tokens/s whose TTFT met the SLO —
(``serving_fleet_goodput_tok_s_*``); ``--disagg`` replays a
prefill-heavy bursty trace through two colocated mixed replicas vs a
disaggregated prefill+decode pair and reports the decode-token p95
(``serving_fleet_decode_token_p95_ms_*``). Both warm every replica
first so the A/B compares serving, not compile stalls.
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax
import jax.numpy as jnp
import numpy as np

from bench import measure_roundtrip_s  # noqa: E402  (scripts on path via cwd)


def _gpt2_model(max_seq_len=1024, dtype=None, **over):
    """One GPT-2-small-shaped serving config + init — shared by every
    measurement here so the stall numbers can never drift to a different
    model than the tick rate they are combined with."""
    from pytorch_distributed_tpu.models.transformer import (
        TransformerConfig,
        TransformerLM,
    )

    cfg = TransformerConfig(
        vocab_size=32000, num_layers=12, num_heads=12, embed_dim=768,
        max_seq_len=max_seq_len,
        dtype=dtype if dtype is not None else jnp.bfloat16,
        attention="dense", **over,
    )
    params = TransformerLM(cfg).init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return cfg, params


def measure(slots: int = 32, max_new: int = 64) -> dict:
    from pytorch_distributed_tpu.models.generate import (
        generate_ragged,
        ragged_prefill,
    )

    cfg, params = _gpt2_model()

    rng = np.random.default_rng(0)
    lengths = rng.integers(16, 257, slots).astype(np.int32)
    prompts = np.zeros((slots, 256), np.int32)
    for i, l in enumerate(lengths):
        prompts[i, :l] = rng.integers(1, cfg.vocab_size, l)
    prompts_j = jnp.asarray(prompts)
    lengths_j = jnp.asarray(lengths)

    # prefill latency (compile, then time the steady call)
    pf = jax.jit(lambda p, pr, ln: ragged_prefill(cfg, p, pr, ln))
    cache, last = pf(params, prompts_j, lengths_j)
    float(jnp.sum(last[:, :1]))
    t0 = time.perf_counter()
    cache, last = pf(params, prompts_j, lengths_j)
    float(jnp.sum(last[:, :1]))
    prefill_s = max(
        time.perf_counter() - t0 - measure_roundtrip_s(), 1e-6
    )

    # decode throughput: the full ragged generate (prefill + max_new
    # decode steps); subtract the measured prefill to isolate decode.
    # THREE runs, quoted median + min-max spread: serving decode through
    # the tunnel has shown a ±14% run-to-run band (VERDICT r4 weak #6) —
    # a single sample measures the tunnel's weather, not the decoder.
    out = generate_ragged(cfg, params, prompts_j, lengths_j,
                          jax.random.key(1), max_new_tokens=max_new)
    int(np.asarray(out)[0, 0])  # compile + drain
    decode_rates = []
    for _ in range(3):
        t0 = time.perf_counter()
        out = generate_ragged(cfg, params, prompts_j, lengths_j,
                              jax.random.key(1), max_new_tokens=max_new)
        int(np.asarray(out)[0, 0])
        total_s = max(
            time.perf_counter() - t0 - measure_roundtrip_s(), 1e-6
        )
        decode_s = max(total_s - prefill_s, 1e-6)
        decode_rates.append(slots * max_new / decode_s)
    decode_tok_s = float(np.median(decode_rates))

    return {
        "serving_slots": slots,
        "serving_prompt_lens": f"{int(lengths.min())}-{int(lengths.max())}",
        "serving_max_new_tokens": max_new,
        "serving_prefill_ms": round(prefill_s * 1e3, 1),
        "serving_prefill_prompt_tok_s": round(
            float(lengths.sum()) / prefill_s
        ),
        "serving_decode_tok_s": round(decode_tok_s),
        "serving_decode_tok_s_min": round(min(decode_rates)),
        "serving_decode_tok_s_max": round(max(decode_rates)),
        # per-TICK latency (all slots advance one token per tick)
        "serving_decode_ms_per_token": round(
            slots * 1e3 / decode_tok_s, 2
        ),
        "device": str(jax.devices()[0]),
    }


def measure_admission_stall(slots: int = 32, n: int = 10,
                            tick_ms: float | None = None) -> dict:
    """Per-admission decode stall of the ContinuousBatcher (VERDICT r4
    next #7).

    ``submit`` runs a full batch-1 prefill + row insert while every
    active decode lane waits — that wall time IS the stall each
    admission imposes on the other ``slots-1`` requests. Measured as
    DEVICE program time (chained dispatch, one scalar sync, round-trip
    subtracted — the tunnel's ~95 ms host hop would otherwise swamp the
    ~17 ms program; on a real TPU VM the host hop is microseconds).
    Reported per prefill bucket, plus the closed-form steady-state
    throughput under Poisson arrivals at the equilibrium rate
    (every completed request replaced: λ_eq = slots / T_request), which
    is what a Poisson trace converges to when the system is kept full.
    """
    from pytorch_distributed_tpu.models.generate import ContinuousBatcher

    cfg, params = _gpt2_model()
    # the DENSE layout's stall — the number the paged engine exists to
    # beat; measure_paged_admission reports the paged counterpart
    b = ContinuousBatcher(cfg, params, n_slots=slots, prefill_bucket=128,
                          cache_layout="dense")

    rng = np.random.default_rng(0)
    out: dict = {"serving_stall_slots": slots}

    # per-bucket SUBMIT program time — prefill + in-program row insert
    # (one donated program; the standalone insert measured ~8 ms of
    # full-cache copy, which dies when the write shares the producer's
    # program). This wall time is exactly the stall every active decode
    # lane sees per admission.
    stall_by_bucket = {}
    slot = jnp.asarray(0)
    for width in (128, 256):
        prompt = jnp.asarray(
            rng.integers(1, cfg.vocab_size, (1, width)).astype(np.int32)
        )
        length = jnp.asarray([width - 7], jnp.int32)
        for _ in range(3):  # compile + settle donation/layout
            b.cache, b.logits = b._submit_one(
                params, prompt, length, b.cache, b.logits, slot
            )
        float(jnp.sum(b.logits[:1, :1]))
        t0 = time.perf_counter()
        for _ in range(n):
            b.cache, b.logits = b._submit_one(
                params, prompt, length, b.cache, b.logits, slot
            )
        float(jnp.sum(b.logits[:1, :1]))
        dt = time.perf_counter() - t0
        stall_by_bucket[width] = (
            max(dt - measure_roundtrip_s(), dt / 2) / n * 1e3
        )
        out[f"serving_admission_stall_ms_b{width}"] = round(
            stall_by_bucket[width], 2
        )

    # decode tick time from the spread-quoted headline measurement
    # (pass tick_ms when the caller already ran measure() — bench.py)
    if tick_ms is None:
        tick_ms = measure(slots=slots, max_new=64)[
            "serving_decode_ms_per_token"
        ]
    out["serving_decode_tick_ms"] = tick_ms

    # Steady state under Poisson arrivals at the equilibrium rate (system
    # kept full): each request = one admission stall + max_new ticks
    # shared with the other slots. Effective tok/s =
    # slots*max_new / (slots*stall + max_new*tick).
    stall = stall_by_bucket[256]  # median prompt ~200 tokens → 256 bucket
    for max_new in (64, 256):
        eff = slots * max_new / (
            slots * stall + max_new * tick_ms
        ) * 1e3
        out[f"serving_equilibrium_tok_s_new{max_new}"] = round(eff)
        out[f"serving_admission_overhead_frac_new{max_new}"] = round(
            slots * stall / (slots * stall + max_new * tick_ms), 3
        )
    return out


def measure_paged_admission(slots: int = 32, n: int = 10,
                            tick_ms: float | None = None) -> dict:
    """Per-admission cost of the PAGED engine (the round-6 tentpole) and
    the equilibrium short-output throughput model it implies — the
    admission-heavy workload where the dense layout paid its ~30% tax.

    An admission here is ``ContinuousBatcher.submit`` on the default
    paged layout: block-chain allocation (host) + one chunk program per
    prompt chunk writing into FRESH blocks — O(prompt), never touching
    resident KV. Timed as chained dispatch over ``n`` admissions into
    distinct slots with ONE sync, round-trip subtracted (same method as
    the dense stall). Reported per prefill-chunk bucket alongside the
    same closed-form equilibrium throughput the dense measurement uses,
    so ``serving_paged_admission_overhead_frac_new64`` is directly
    comparable with ``serving_admission_overhead_frac_new64``.
    """
    from pytorch_distributed_tpu.models.generate import ContinuousBatcher

    cfg, params = _gpt2_model()
    b = ContinuousBatcher(cfg, params, n_slots=slots, prefill_bucket=128)
    rng = np.random.default_rng(0)
    out: dict = {
        "serving_paged_block_len": b.engine.block_len,
        "serving_paged_chunk": b.engine.chunk,
    }

    stall_by_bucket = {}
    for width in (128, 256):
        prompt = rng.integers(
            1, cfg.vocab_size, (width - 7,)
        ).astype(np.int32)
        for _ in range(2):  # compile + settle donation
            b.submit(prompt, 1)
            b.step()  # budget 1: retires, frees the slot and its blocks
        jax.block_until_ready(b.logits)
        t0 = time.perf_counter()
        for _ in range(n):
            b.submit(prompt, 1)
        jax.block_until_ready(b.logits)
        dt = time.perf_counter() - t0
        while any(b.remaining > 0):
            b.step()
        stall_by_bucket[width] = (
            max(dt - measure_roundtrip_s(), dt / 2) / n * 1e3
        )
        out[f"serving_paged_admission_stall_ms_b{width}"] = round(
            stall_by_bucket[width], 2
        )

    if tick_ms is None:
        tick_ms = measure(slots=slots, max_new=64)[
            "serving_decode_ms_per_token"
        ]
    stall = stall_by_bucket[256]
    for max_new in (64, 256):
        eff = slots * max_new / (slots * stall + max_new * tick_ms) * 1e3
        out[f"serving_paged_equilibrium_tok_s_new{max_new}"] = round(eff)
        out[f"serving_paged_admission_overhead_frac_new{max_new}"] = round(
            slots * stall / (slots * stall + max_new * tick_ms), 3
        )
    return out


def measure_paged_latency(slots: int = 16, requests: int = 48,
                          max_new: int = 32, trace=None,
                          tick_s: float = 1.0) -> dict:
    """End-to-end latency percentiles of the paged scheduler under a
    queued multi-tenant workload (ISSUE 4: the one metric a
    vLLM/Orca-style continuous batcher exists to control, previously
    unreported). Drives ``serving.Scheduler`` with ``requests`` random
    prompts (3x oversubscribed vs ``slots``), exact host-side TTFT /
    per-output-token / queue-wait series from the scheduler's own
    timestamps — no extra syncs beyond the token fetch every tick
    already pays.

    Pass ``trace`` (round 10: a ``fleet.traffic`` trace, e.g. from
    ``--gen-trace``) to replace the all-at-once equilibrium submission
    with seeded bursty heavy-tail arrivals replayed in the step domain
    — the same file the fleet benches consume."""
    from pytorch_distributed_tpu.serving import Scheduler

    cfg, params = _gpt2_model()
    rng = np.random.default_rng(0)
    sched = Scheduler(cfg, params, n_slots=slots, prefill_chunk=64,
                      admit_per_step=4)
    if trace is not None:
        from pytorch_distributed_tpu.fleet import (
            clamp_trace,
            prompt_for,
            replay_trace,
        )

        trace = clamp_trace(trace, cfg.max_seq_len, sched.engine.chunk)
        requests = len(trace)
        replay_trace(
            trace,
            lambda r: sched.submit(prompt_for(r, cfg.vocab_size),
                                   r.max_new),
            sched.step,
            lambda: not sched.queue and not sched.resident,
            tick_s=tick_s,
        )
    else:
        lens = rng.integers(16, 257, requests)
        for l in lens:
            sched.submit(
                rng.integers(1, cfg.vocab_size,
                             size=int(l)).astype(np.int32),
                max_new,
            )
        sched.drain()
    m = sched.metrics()
    out = {
        "serving_paged_lat_slots": slots,
        "serving_paged_lat_requests": requests,
        "serving_paged_lat_traffic": (
            "trace" if trace is not None else "equilibrium"
        ),
        "serving_paged_lat_max_new": max_new,
        "serving_paged_tokens_per_s": round(m["tokens_per_s"], 1),
    }
    for name in ("ttft", "token_lat", "queue_wait"):
        for q in ("p50", "p95"):
            key = f"{name}_{q}_s"
            if key in m:
                out[f"serving_paged_{name}_{q}_ms"] = round(
                    m[key] * 1e3, 2
                )
    return out


# ---------------------------------------------------------------------------
# fleet layer (round 10): traces, router goodput A/B, disaggregation A/B
# ---------------------------------------------------------------------------


def _tiny_model(max_seq_len=128):
    """Tiny fp32 config for the fleet benches — the router simulation's
    point is scheduling/latency structure, not model FLOPs, and the
    GPT-2 shape would put a CPU A/B in the minutes."""
    import jax.numpy as jnp

    from pytorch_distributed_tpu.models.transformer import (
        TransformerLM,
        tiny_config,
    )

    cfg = tiny_config(attention="dense", max_seq_len=max_seq_len,
                      dtype=jnp.float32)
    params = TransformerLM(cfg).init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return cfg, params


def default_fleet_trace(seed: int = 0, prefill_heavy: bool = False):
    """The bench's stock bursty heavy-tail trace, sized so ~0.46
    requests arrive per tick — above one 4-slot replica's ~0.29/tick
    service capacity (≈ ceil(prompt/chunk) + max_new ticks per request)
    and below two replicas' — the regime where the router A/B is
    meaningful. ``prefill_heavy`` doubles prompt lengths and halves
    outputs (the disaggregation stressor)."""
    from pytorch_distributed_tpu.fleet import generate_trace

    return generate_trace(
        seed=seed, duration_s=240.0, base_rate=0.5,
        burst_rate_mult=4.0, burst_every_s=40.0, burst_len_s=6.0,
        sessions=16,
        prompt_median=48 if prefill_heavy else 24, prompt_sigma=0.8,
        prompt_min=4, prompt_max=96,
        max_new_median=6 if prefill_heavy else 12, max_new_sigma=0.6,
        max_new_min=2, max_new_max=24,
    )


def _replay_fleet(cfg, params, trace, n_replicas, *, disaggregate=False,
                  slo=None, slots=4, tick_s=1.0, warmup=True,
                  seed=0, **router_kwargs):
    """Build a router, warm it, replay the trace; returns
    ``(router, per-request records, wall_s, ticks)`` — records read back
    from a throwaway JSONL stream so goodput-within-SLO can be computed
    from the same per-request schema telemetry_report consumes."""
    import json as _json
    import tempfile

    from pytorch_distributed_tpu.fleet import (
        FleetRouter,
        prompt_for,
        replay_trace,
    )
    from pytorch_distributed_tpu.utils.profiling import MetricsLogger

    with tempfile.NamedTemporaryFile(mode="r", suffix=".jsonl") as tf:
        mlog = MetricsLogger(tf.name)
        router = FleetRouter(
            cfg, params, n_replicas=n_replicas,
            disaggregate=disaggregate, slo=slo, seed=seed,
            metrics_log=mlog, n_slots=slots, block_len=16,
            prefill_chunk=32, admit_per_step=4, **router_kwargs,
        )
        if warmup:
            router.warmup()
        t0 = time.perf_counter()
        ticks = replay_trace(
            trace,
            lambda r: router.submit(prompt_for(r, cfg.vocab_size),
                                    r.max_new, session=r.session),
            router.step,
            lambda: router.idle,
            tick_s=tick_s,
        )
        wall = time.perf_counter() - t0
        mlog.close()
        records = [_json.loads(line) for line in tf.read().splitlines()
                   if line.strip()]
    return router, records, wall, ticks


def _goodput_tok_per_s(records, ticks: int, tick_s: float,
                       slo_ttft_ticks: float) -> float:
    """Completed tokens per NOMINAL second within the SLO: only requests
    whose step-domain TTFT met the target count — the metric a fleet
    exists to maximize (raw tokens/s rewards serving a backlog nobody is
    waiting for). Both the TTFT and the denominator live in the step
    domain (ticks x nominal tick_s): the single-process simulation turns
    every replica's crank from one host loop, so machine wall time is
    shared across replicas and would misprice an N-replica fleet that
    real deployments run on N times the hardware; tick latencies measure
    the SCHEDULE, identically on any host."""
    good = sum(
        r.get("new_tokens", 0) for r in records
        if r.get("kind") == "request" and not r.get("rejected")
        and r.get("ttft_steps", float("inf")) <= slo_ttft_ticks
    )
    return good / max(ticks * tick_s, 1e-9)


def measure_fleet(trace=None, slo_ttft_ticks: float | None = None,
                  slots: int = 4) -> dict:
    """The router A/B (acceptance: ISSUE 7): ONE bursty heavy-tail
    trace, same offered per-tick load, served by 1 replica vs 2 — the
    2-replica router must sustain higher goodput (tokens per nominal
    second whose step-domain TTFT met the SLO; see
    ``_goodput_tok_per_s`` for why the accounting lives in ticks). The
    SLO defaults to 3x the 2-replica fleet's own TTFT p95 in ticks —
    "what a provisioned fleet achieves, with headroom"; the gate (spill
    at queue 4, shed at 24) is identical in both runs, so the single
    replica queues past the SLO and sheds where the pair spills."""
    from pytorch_distributed_tpu.fleet import SLOConfig
    from pytorch_distributed_tpu.telemetry import percentiles

    cfg, params = _tiny_model()
    if trace is None:
        trace = default_fleet_trace()
    slo = SLOConfig(spill_queue_depth=4, shed_queue_depth=24)
    r2, rec2, _, ticks2 = _replay_fleet(cfg, params, trace, 2, slo=slo,
                                        slots=slots)
    r1, rec1, _, ticks1 = _replay_fleet(cfg, params, trace, 1, slo=slo,
                                        slots=slots)
    m2, m1 = r2.metrics(), r1.metrics()

    def ttft_ticks_p95(records):
        ps = percentiles(
            [r["ttft_steps"] for r in records
             if r.get("kind") == "request" and "ttft_steps" in r],
            qs=(95,),
        )
        return ps.get("p95", 0.0)

    if slo_ttft_ticks is None:
        slo_ttft_ticks = 3.0 * max(ttft_ticks_p95(rec2), 1.0)
    g2 = _goodput_tok_per_s(rec2, ticks2, 1.0, slo_ttft_ticks)
    g1 = _goodput_tok_per_s(rec1, ticks1, 1.0, slo_ttft_ticks)
    return {
        "serving_fleet_trace_requests": len(trace),
        "serving_fleet_slots_per_replica": slots,
        "serving_fleet_slo_ttft_ticks": round(slo_ttft_ticks, 1),
        "serving_fleet_goodput_tok_s_1r": round(g1, 2),
        "serving_fleet_goodput_tok_s_2r": round(g2, 2),
        "serving_fleet_goodput_ratio_2r_over_1r": round(
            g2 / max(g1, 1e-9), 2
        ),
        "serving_fleet_shed_rate_1r": round(m1["shed_rate"], 4),
        "serving_fleet_shed_rate_2r": round(m2["shed_rate"], 4),
        "serving_fleet_spill_rate_2r": round(m2["spill_rate"], 4),
        "serving_fleet_ttft_p95_ticks_1r": round(ttft_ticks_p95(rec1), 1),
        "serving_fleet_ttft_p95_ticks_2r": round(ttft_ticks_p95(rec2), 1),
        "serving_fleet_recommend_peak_1r": m1["recommended_replicas_peak"],
        "device": str(jax.devices()[0]),
    }


def measure_disagg(trace=None, slots: int = 4) -> dict:
    """The disaggregation A/B (acceptance: ISSUE 7): a prefill-heavy
    bursty trace through (a) two COLOCATED mixed replicas and (b) one
    prefill + one decode replica (decode sized 2x — a decode slot is
    held ~max_new ticks vs ~ceil(prompt/chunk) for prefill; sizing roles
    independently is disaggregation's point).

    The headline is decode-token p95 as REPLICA-ATTRIBUTED latency —
    the wall cost of the serving replica's own token-producing tick
    (``Scheduler.tick_lat``). Colocated, a resident stream's token is
    data-dependent on the chunk program sharing its pool and device, so
    prefill bursts land inside every stream's tick; disaggregated, the
    decode replica's tick runs decode only and the burst cost collapses
    into the counted, timed KV handoffs. (The raw inter-token wall gap
    is reported too, but in this one-loop single-host simulation it
    sums EVERY replica's step — real fleets run replicas on separate
    hosts — so the replica-attributed number is the honest one; same
    simulation-correction argument as the step-domain goodput.) TTFT
    for both sides is reported — the handoff queue makes disaggregated
    TTFT worse; that tradeoff is the point."""
    cfg, params = _tiny_model()
    if trace is None:
        trace = default_fleet_trace(prefill_heavy=True)
    rc, recc, _, _ = _replay_fleet(cfg, params, trace, 2, slots=slots)
    rd, recd, _, _ = _replay_fleet(cfg, params, trace, 2,
                                   disaggregate=True, slots=slots,
                                   decode_slots=2 * slots,
                                   handoffs_per_tick=2)
    mc, md = rc.metrics(), rd.metrics()

    def tick_p95_ms(router, roles):
        from pytorch_distributed_tpu.telemetry import percentiles

        vals = [v for s, role in zip(router.replicas, router.roles)
                if role in roles for v in s.tick_lat.values]
        return percentiles(vals, qs=(95,)).get("p95", 0.0) * 1e3

    def gap_p95_ms(records):
        from pytorch_distributed_tpu.telemetry import percentiles

        gaps = [g for r in records if r.get("kind") == "request"
                for g in r.get("token_gaps_s", [])]
        return percentiles(gaps, qs=(95,)).get("p95", 0.0) * 1e3

    pc = tick_p95_ms(rc, ("mixed",))
    pd = tick_p95_ms(rd, ("decode",))
    return {
        "serving_fleet_disagg_trace_requests": len(trace),
        "serving_fleet_decode_token_p95_ms_colocated": round(pc, 2),
        "serving_fleet_decode_token_p95_ms_disagg": round(pd, 2),
        "serving_fleet_decode_p95_ratio_colo_over_disagg": round(
            pc / max(pd, 1e-9), 2
        ),
        "serving_fleet_loop_gap_p95_ms_colocated": round(
            gap_p95_ms(recc), 2
        ),
        "serving_fleet_loop_gap_p95_ms_disagg": round(
            gap_p95_ms(recd), 2
        ),
        "serving_fleet_handoffs": md["handoffs"],
        "serving_fleet_handoff_ms_mean": round(
            md.get("handoff_mean_s", 0.0) * 1e3, 2
        ),
        "serving_fleet_ttft_p95_ms_colocated": round(
            mc.get("ttft_p95_s", 0.0) * 1e3, 1
        ),
        "serving_fleet_ttft_p95_ms_disagg": round(
            md.get("ttft_p95_s", 0.0) * 1e3, 1
        ),
        "device": str(jax.devices()[0]),
    }


def measure_tp_virtual(slots: int = 8, tp: int = 2) -> dict:
    """TP batcher decode rate on the VIRTUAL CPU mesh — a functionality
    row, not a performance claim (tp>1 needs more chips than this
    environment has; re-measure on real multi-chip hardware). Parity is
    tested in tests/test_serving_tp.py."""
    import dataclasses

    from pytorch_distributed_tpu.models.generate import generate_ragged_tp
    from pytorch_distributed_tpu.parallel import make_mesh

    if len(jax.devices()) < tp:
        return {"serving_tp_error": f"needs {tp} devices"}
    # ONE init with the replicated twin (a TP config cannot init outside
    # shard_map — tp_reduce's psum has no axis); the TP cfg is a replace
    rep, params = _gpt2_model(max_seq_len=512, dtype=jnp.float32)
    cfg = dataclasses.replace(rep, model_axis="model", tp_size=tp)
    mesh = make_mesh(jax.devices()[:tp], data_parallel=1, seq_parallel=1,
                     model_parallel=tp)
    rng = np.random.default_rng(0)
    lengths = rng.integers(16, 129, slots).astype(np.int32)
    prompts = np.zeros((slots, 128), np.int32)
    for i, l in enumerate(lengths):
        prompts[i, :l] = rng.integers(1, cfg.vocab_size, l)
    args = (jnp.asarray(prompts), jnp.asarray(lengths),
            jax.random.key(1))
    out = generate_ragged_tp(mesh, cfg, params, *args, max_new_tokens=16)
    int(np.asarray(out)[0, 0])
    t0 = time.perf_counter()
    out = generate_ragged_tp(mesh, cfg, params, *args, max_new_tokens=16)
    int(np.asarray(out)[0, 0])
    dt = time.perf_counter() - t0
    return {
        "serving_tp_virtual_tok_s": round(slots * 16 / dt),
        "serving_tp_degree": tp,
        "serving_tp_note": "virtual CPU mesh: functionality, not perf",
    }


def measure_gather_ab(slots: int = 8, ticks: int = 32, prompt_len: int = 64,
                      tiny: bool = False, block_len: int = 16,
                      tuned_dir=None) -> dict:
    """Pallas-vs-dense gather A/B (ISSUE 10) + int8-vs-bf16 pool
    capacity at fixed bytes, as bench-style JSON for
    ``bench_regression.py``.

    Decode side: every slot holds a ``prompt_len`` KV chain, then
    ``ticks`` full decode ticks run per gather spelling on a WARM
    program (one untimed tick first) — tokens materialize inside
    ``engine.decode``, so each tick's wall is dispatch + device + sync.
    Reports decode-tok/s and decode-tick p95 for each spelling plus the
    pallas/dense ratio. HONESTY: on a non-TPU backend the pallas
    spelling runs the Pallas INTERPRETER (``gather_ab_backend`` says
    which); the ratio is a TPU performance claim and a CPU correctness/
    plumbing exercise — do not regress-gate the CPU ratio
    (ANALYSIS.md "Paged attention kernel & quantized KV").

    Capacity side: ``kv_pool.pool_block_bytes`` arithmetic on the bf16
    twin of the same config — blocks a fixed 64 MiB budget fits, raw
    bf16 vs int8+scales (exactly 2D/(D+4), 1.88x at the GPT-2 head
    dim)."""
    import dataclasses

    from pytorch_distributed_tpu.serving import PagedEngine
    from pytorch_distributed_tpu.serving.engine import ChunkJob
    from pytorch_distributed_tpu.serving.kv_pool import pool_block_bytes

    if tiny:
        cfg, params = _tiny_model(max_seq_len=256)
    else:
        cfg, params = _gpt2_model(max_seq_len=512)
    rng = np.random.default_rng(0)
    prompt = rng.integers(1, cfg.vocab_size, prompt_len).astype(np.int32)
    chunk = prompt_len  # one prefill program fills every chain

    def decode_side(gather_impl, kv_dtype=None, split_s=None,
                    autotune_dir=None, bl=None):
        eng = PagedEngine(cfg, params, slots,
                          block_len=block_len if bl is None else bl,
                          prefill_chunk=chunk, gather_impl=gather_impl,
                          kv_dtype=kv_dtype, split_s=split_s,
                          autotune_dir=autotune_dir)
        for s in range(slots):
            assert eng.admit(s, prompt_len, ticks + 1)
        eng.run_chunks([
            ChunkJob(slot=s, tokens=prompt, start=0, is_last=True,
                     last_idx=prompt_len - 1)
            for s in range(slots)
        ])
        positions = np.full(slots, prompt_len, np.int32)
        active = np.ones(slots, bool)
        key = jax.random.key(1)
        _tokens, positions = eng.decode(positions, active, key)  # warm
        times = []
        for _ in range(ticks):
            t0 = time.perf_counter()
            _tokens, positions = eng.decode(positions, active, key)
            times.append(time.perf_counter() - t0)
        return {
            "tok_s": round(slots * ticks / sum(times), 1),
            "tick_p95_ms": round(
                float(np.percentile(times, 95)) * 1e3, 3
            ),
        }

    dense = decode_side("dense")
    pallas = decode_side("pallas")
    # round 20 columns: fp8 pool decode, forced split-S decode (the
    # flash-decoding path even when W/B sits under the auto threshold),
    # and — with --tuned — the autotuned config vs the defaults.
    # Same honesty rule as the dense/pallas ratio: off-TPU these time
    # the Pallas INTERPRETER (gather_ab_backend says which).
    fp8 = decode_side("pallas", kv_dtype="fp8")
    split = decode_side("pallas", split_s=2)
    bf16_cfg = dataclasses.replace(cfg, dtype=jnp.bfloat16)
    bf16_block = pool_block_bytes(bf16_cfg, params, block_len)
    int8_block = pool_block_bytes(bf16_cfg, params, block_len,
                                  kv_dtype="int8")
    fp8_block = pool_block_bytes(bf16_cfg, params, block_len,
                                 kv_dtype="fp8")
    budget = 64 << 20
    out = {
        "gather_ab_backend": jax.default_backend(),
        "gather_ab_slots": slots,
        "gather_ab_ticks": ticks,
        "gather_ab_prompt_len": prompt_len,
        "serving_gather_ab_decode_tok_s_dense": dense["tok_s"],
        "serving_gather_ab_decode_tok_s_pallas": pallas["tok_s"],
        "serving_gather_ab_decode_tick_p95_ms_dense": dense["tick_p95_ms"],
        "serving_gather_ab_decode_tick_p95_ms_pallas": pallas["tick_p95_ms"],
        "serving_gather_ab_pallas_over_dense": round(
            pallas["tok_s"] / dense["tok_s"], 3
        ),
        "serving_kernel_decode_tok_s_fp8": fp8["tok_s"],
        "serving_kernel_decode_tick_p95_ms_fp8": fp8["tick_p95_ms"],
        "serving_kernel_decode_tok_s_split2": split["tok_s"],
        "serving_kernel_decode_tick_p95_ms_split2": split["tick_p95_ms"],
        "serving_kernel_fp8_over_pallas": round(
            fp8["tok_s"] / pallas["tok_s"], 3
        ),
        "serving_kernel_split2_over_pallas": round(
            split["tok_s"] / pallas["tok_s"], 3
        ),
        "serving_kv_pool_block_bytes_bf16": bf16_block,
        "serving_kv_pool_block_bytes_int8": int8_block,
        "serving_kv_pool_block_bytes_fp8": fp8_block,
        "serving_kv_pool_blocks_at_64mb_bf16": budget // bf16_block,
        "serving_kv_pool_blocks_at_64mb_int8": budget // int8_block,
        "serving_kv_pool_blocks_at_64mb_fp8": budget // fp8_block,
        "serving_kv_pool_capacity_ratio_int8_over_bf16": round(
            (budget // int8_block) / (budget // bf16_block), 3
        ),
        "serving_kv_pool_capacity_ratio_fp8_over_bf16": round(
            (budget // fp8_block) / (budget // bf16_block), 3
        ),
    }
    if tuned_dir is not None:
        # --tuned: A/B the autotuned config (scripts/autotune.py output,
        # loaded by the engine keyed by fingerprint) against the default
        # pallas engine timed above. tuned_loaded says whether a tuned
        # file actually matched — a clean miss A/Bs default-vs-default,
        # honestly labeled rather than silently skipped.
        from pytorch_distributed_tpu.serving.engine import PagedEngine

        probe_eng = PagedEngine(cfg, params, slots,
                                gather_impl="pallas",
                                autotune_dir=tuned_dir)
        tuned = decode_side("pallas", autotune_dir=tuned_dir)
        out.update({
            "serving_kernel_tuned_loaded": probe_eng.tuned is not None,
            "serving_kernel_tuned_block_len": probe_eng.block_len,
            "serving_kernel_tuned_split_s": probe_eng.config.split_s,
            "serving_kernel_decode_tok_s_tuned": tuned["tok_s"],
            "serving_kernel_decode_tick_p95_ms_tuned":
                tuned["tick_p95_ms"],
            "serving_kernel_tuned_over_default": round(
                tuned["tok_s"] / pallas["tok_s"], 3
            ),
        })
    return out


def measure_pressure(trace=None, slots: int = 4, n_blocks: int = 13,
                     sessions: int = 100_000,
                     duration_s: float = 90.0) -> dict:
    """The pressure-tier A/B (ISSUE 11): ONE over-committed bursty trace
    (sessions ≫ pool chains — default 100k session ids over a pool that
    holds ~3 chains per replica) served by (a) a shed-only fleet (the
    pre-round-13 ladder: queue then reject) and (b) the same fleet with
    the KV pressure tier on (host offload + the SLO gate's preempt
    rung). The headline is goodput — completed tokens per nominal
    second whose step-domain TTFT met the SLO (same accounting as
    ``measure_fleet``) — plus the shed rates the preempt rung exists to
    zero and the measured swap walls behind the decision model."""
    from pytorch_distributed_tpu.fleet import SLOConfig, generate_trace
    from pytorch_distributed_tpu.telemetry import percentiles

    cfg, params = _tiny_model()
    if trace is None:
        trace = generate_trace(
            seed=0, duration_s=duration_s, base_rate=0.7,
            burst_rate_mult=4.0, burst_every_s=20.0, burst_len_s=4.0,
            sessions=sessions,
            prompt_median=24, prompt_sigma=0.8, prompt_min=4,
            prompt_max=96, max_new_median=10, max_new_sigma=0.6,
            max_new_min=2, max_new_max=24,
        )
    slo = SLOConfig(spill_queue_depth=2, shed_queue_depth=8)
    shed_only, rec_s, _, ticks_s = _replay_fleet(
        cfg, params, trace, 2, slo=slo, slots=slots, n_blocks=n_blocks,
    )
    pressured, rec_p, _, ticks_p = _replay_fleet(
        cfg, params, trace, 2, slo=slo, slots=slots, n_blocks=n_blocks,
        offload=True, preempt_on_oom=True,
    )
    ms, mp = shed_only.metrics(), pressured.metrics()

    def ttft_ticks_p95(records):
        ps = percentiles(
            [r["ttft_steps"] for r in records
             if r.get("kind") == "request" and "ttft_steps" in r],
            qs=(95,),
        )
        return ps.get("p95", 0.0)

    slo_ttft_ticks = 3.0 * max(ttft_ticks_p95(rec_p), 1.0)
    g_shed = _goodput_tok_per_s(rec_s, ticks_s, 1.0, slo_ttft_ticks)
    g_pre = _goodput_tok_per_s(rec_p, ticks_p, 1.0, slo_ttft_ticks)
    swaps = [r for r in rec_p if r.get("kind") == "swap" and r.get("ok")]
    swap_walls = [r["wall_s"] for r in swaps if "wall_s" in r]
    swap_p95 = percentiles(swap_walls, qs=(95,)).get("p95", 0.0)
    return {
        "serving_pressure_trace_requests": len(trace),
        "serving_pressure_sessions": sessions,
        "serving_pressure_pool_blocks": n_blocks,
        "serving_pressure_slo_ttft_ticks": round(slo_ttft_ticks, 1),
        "serving_pressure_goodput_tok_s_shed_only": round(g_shed, 2),
        "serving_pressure_goodput_tok_s_preempt": round(g_pre, 2),
        "serving_pressure_goodput_ratio": round(
            g_pre / max(g_shed, 1e-9), 2
        ),
        "serving_pressure_shed_rate_shed_only": round(
            ms["shed_rate"], 4
        ),
        "serving_pressure_shed_rate_preempt": round(mp["shed_rate"], 4),
        "serving_pressure_sheds_preempt": mp["shed"],
        "serving_pressure_preempts": mp["preempts"],
        "serving_pressure_restores": mp["restores"],
        "serving_pressure_swap_mib": round(
            mp["swap_bytes"] / 2**20, 2
        ),
        "serving_pressure_swap_p95_ms": round(swap_p95 * 1e3, 3),
        "device": str(jax.devices()[0]),
    }


def measure_prefix(trace=None, slots: int = 8, prefix_len: int = 64,
                   replicas: int = 2, out_path: str = None) -> dict:
    """The prefix-sharing A/B (ISSUE 15): ONE seeded shared-system-
    prompt trace — every request is a ``prefix_len``-token shared
    system prefix plus its own heavy-tail tail
    (``fleet.shared_prefix_prompt_for``) — served by the same 2-replica
    session-affinity fleet with the radix prefix cache OFF and ON.

    Headline: **admitted-prefill tokens per request** (the prompt
    tokens the chunk programs actually process at admission — a hit
    skips its covered prefix; the acceptance gate wants >= 2x lower
    with sharing on) plus admission latency, fresh pool blocks
    allocated per request, hit rate, COW copies, and a token-identity
    check (greedy streams must be bit-equal across the A/B, prefix off
    vs on). Wall-millisecond magnitudes are backend-marked
    (``gather_ab_backend`` convention): on the CPU simulation they
    describe host scheduling, not TPU serving."""
    import dataclasses as _dc
    import tempfile

    from pytorch_distributed_tpu.fleet import (
        FleetRouter,
        SLOConfig,
        generate_trace,
        replay_trace,
        shared_prefix_prompt_for,
    )
    from pytorch_distributed_tpu.utils.profiling import MetricsLogger

    cfg, params = _tiny_model()
    if trace is None:
        trace = generate_trace(
            seed=0, duration_s=120.0, base_rate=0.5,
            burst_rate_mult=4.0, burst_every_s=30.0, burst_len_s=4.0,
            sessions=8,
            prompt_median=12, prompt_sigma=0.8, prompt_min=4,
            prompt_max=32, max_new_median=6, max_new_sigma=0.6,
            max_new_min=2, max_new_max=12,
        )
    # fit prefix + tail + decode budget into the config (the shared
    # prefix rides on TOP of the trace's prompt_len)
    tail_max = max(4, (cfg.max_seq_len - prefix_len) // 3)
    new_max = max(2, (cfg.max_seq_len - prefix_len) // 8)
    trace = [
        _dc.replace(r, prompt_len=min(r.prompt_len, tail_max),
                    max_new=min(r.max_new, new_max))
        for r in trace
    ]
    slo = SLOConfig(spill_queue_depth=4, shed_queue_depth=64,
                    prefix_sticky_depth=8)

    def run(prefix_on, path):
        mlog = MetricsLogger(path)
        router = FleetRouter(
            cfg, params, n_replicas=replicas, slo=slo, seed=0,
            metrics_log=mlog, n_slots=slots, block_len=16,
            prefill_chunk=32, admit_per_step=4,
            prefix_cache=prefix_on,
        )
        router.warmup()
        t0 = time.perf_counter()
        ticks = replay_trace(
            trace,
            lambda r: router.submit(
                shared_prefix_prompt_for(r, cfg.vocab_size, prefix_len),
                r.max_new, session=r.session,
            ),
            router.step,
            lambda: router.idle,
        )
        wall = time.perf_counter() - t0
        m = router.metrics()
        router.log_summary()
        # exact admission latency across the fleet (weighted by each
        # replica's admissions, steps and wall both)
        per = [s.metrics() for s in router.replicas]
        admitted = sum(p["admitted"] for p in per) or 1
        adm_steps = sum(
            p["admission_latency_steps_mean"] * p["admitted"] for p in per
        ) / admitted
        adm_s = sum(
            p["admission_latency_s_mean"] * p["admitted"] for p in per
        ) / admitted
        fresh = sum(
            s.engine.allocator.fresh_allocated for s in router.replicas
        )
        m["admitted"] = sum(p["admitted"] for p in per)
        mlog.close()
        return router, m, ticks, wall, adm_steps, adm_s, fresh

    with tempfile.NamedTemporaryFile(suffix=".jsonl") as tf:
        r_off, m_off, _, wall_off, st_off, s_off, fresh_off = run(
            False, tf.name
        )
    r_on, m_on, _, wall_on, st_on, s_on, fresh_on = run(
        True, out_path if out_path else None
    )
    reqs = max(m_on["completed"], 1)
    tok_on = m_on["admitted_prefill_tokens"] / max(m_on["admitted"], 1)
    tok_off = m_off["admitted_prefill_tokens"] / max(m_off["admitted"], 1)
    identical = r_on.results == r_off.results
    return {
        "serving_prefix_trace_requests": len(trace),
        "serving_prefix_prefix_len": prefix_len,
        "serving_prefix_replicas": replicas,
        "serving_prefix_hit_rate": round(m_on["prefix_hit_rate"], 4),
        "serving_prefix_covered_frac": round(
            m_on["prefix_covered_tokens"]
            / max(m_on["prefix_covered_tokens"]
                  + m_on["admitted_prefill_tokens"], 1), 4
        ),
        "serving_prefix_admit_tok_per_req_on": round(tok_on, 2),
        "serving_prefix_admit_tok_per_req_off": round(tok_off, 2),
        "serving_prefix_admit_tok_ratio_off_over_on": round(
            tok_off / max(tok_on, 1e-9), 2
        ),
        "serving_prefix_fresh_blocks_per_req_on": round(
            fresh_on / max(m_on["admitted"], 1), 2
        ),
        "serving_prefix_fresh_blocks_per_req_off": round(
            fresh_off / max(m_off["admitted"], 1), 2
        ),
        "serving_prefix_admission_steps_mean_on": round(st_on, 2),
        "serving_prefix_admission_steps_mean_off": round(st_off, 2),
        "serving_prefix_admission_ms_mean_on": round(s_on * 1e3, 3),
        "serving_prefix_admission_ms_mean_off": round(s_off * 1e3, 3),
        "serving_prefix_cow_copies": m_on["prefix_cow_copies"],
        "serving_prefix_evictions": m_on["prefix_evictions"],
        "serving_prefix_shared_blocks_peak": m_on["prefix_shared_blocks"],
        "serving_prefix_completed": reqs,
        "serving_prefix_tokens_identical": identical,
        "serving_prefix_wall_s_on": round(wall_on, 2),
        "serving_prefix_wall_s_off": round(wall_off, 2),
        # CPU-honesty label (gather_ab_backend convention, PR 10): the
        # token-accounting claims hold anywhere; the wall/ms magnitudes
        # are TPU claims only when this says tpu
        "serving_prefix_backend": jax.default_backend(),
        "device": str(jax.devices()[0]),
    }


# ---------------------------------------------------------------------------
# wall-clock fleet bench (round 15): the overlap profiler's headline —
# the measurement contract ROADMAP item 3's async host refactor gates on
# ---------------------------------------------------------------------------


def _wallclock_side(cfg, params, trace, n_replicas, slots, out_path=None,
                    async_host=False):
    """One saturated wall-clock run: every arrival submitted up front
    (tokenized under a ledger mark), then the fleet loop cranked
    back-to-back until idle — no nominal tick. Unlike the step-domain
    benches this measures MACHINE wall, which is exactly the point: the
    one-loop router serializes replica host work, and the ledger's
    per-replica device timeline attributes every second of it.

    ``async_host=True`` (round 16) runs the dispatch-then-collect loop:
    tokenization fans out over the router's ``HostWorkerPool`` (the
    marks carry worker-thread names), replica ticks launch back-to-back
    and collect lagged, per-request JSONL rides the workers."""
    from pytorch_distributed_tpu.fleet import (
        FleetRouter,
        SLOConfig,
        prompt_for,
    )
    from pytorch_distributed_tpu.telemetry import (
        DispatchLedger,
        ReqTracer,
        busy_summary,
        cause_histogram,
        fleet_busy_summary,
    )
    from pytorch_distributed_tpu.utils.profiling import MetricsLogger

    mlog = MetricsLogger(out_path)
    reqtrace = ReqTracer(mlog)
    ledger = DispatchLedger(mlog, seq_source=reqtrace)
    router = FleetRouter(
        cfg, params, n_replicas=n_replicas,
        # saturation bench: spills balance load, sheds would change the
        # served token count between the 1r and Nr sides
        slo=SLOConfig(spill_queue_depth=4, shed_queue_depth=10**6),
        metrics_log=mlog, reqtrace=reqtrace, ledger=ledger,
        async_host=async_host,
        n_slots=slots, block_len=16, prefill_chunk=32, admit_per_step=4,
    )
    router.warmup()  # the A/B compares serving, not compile stalls
    ordered = sorted(trace, key=lambda r: (r.t, r.rid))
    t0 = time.perf_counter()
    if async_host:
        # threaded tokenize: the per-request token-stream builds fan out
        # over the worker pool (deterministic per request — order of
        # COMPLETION is free), then submission happens in trace order so
        # routing matches the synchronous side request-for-request
        prompts = [None] * len(ordered)

        def _tok(idx, r):
            def work():
                with ledger.host("tokenize/detokenize"):
                    prompts[idx] = prompt_for(r, cfg.vocab_size)
            return work

        for idx, r in enumerate(ordered):
            router.host_pool.submit(_tok(idx, r))
        router.host_pool.flush()
        for idx, r in enumerate(ordered):
            router.submit(prompts[idx], r.max_new, session=r.session)
    else:
        for r in ordered:
            with ledger.host("tokenize/detokenize"):
                prompt = prompt_for(r, cfg.vocab_size)
            router.submit(prompt, r.max_new, session=r.session)
    while not router.idle:
        router.step()
    wall = time.perf_counter() - t0
    router.log_summary()
    ledger.finalize()
    mlog.close()
    m = router.metrics()
    records = ledger.snapshot()
    return {
        "wall_s": wall,
        "tokens": m["tokens_out"],
        "tok_s": m["tokens_out"] / max(wall, 1e-9),
        "shed": m["shed"],
        "busy": busy_summary(records),
        "union": fleet_busy_summary(records),
        "causes": cause_histogram(records),
    }


def _async_gap_decomposition(side_async, side_1, n: int) -> dict:
    """The async loop's efficiency-gap accounting (round 16). The sync
    loop's gap was host serialization, and per-replica bubbles covered
    it; under dispatch-then-collect the per-replica dispatch→completion
    windows legitimately overlap on a shared device, so the honest
    accounting decomposes the remaining gap into measured, attributable
    parts (aggregate stream-seconds, gap = n × (wall − ideal)):

    - ``idle``: the UNION-timeline device-idle seconds — true bubbles,
      the only part more host-overlap engineering could still remove;
    - ``overwork``: union busy beyond the 1-replica device seconds per
      token — N half-empty replicas each run their own tick programs,
      burning more device time per token than one full replica;
    - ``shared_device``: the floor from N replicas sharing ONE device —
      1r device busy per token × N exceeds the perfect-scaling wall by
      construction whenever 1r busy fraction > 1/N. Vanishes on real
      N-device hardware (the CPU-backend honesty term);
    - ``edge``: host wall outside the ledger window (tokenize/submit
      before the first dispatch, finalize after the last completion).

    The four parts tile the gap algebraically; reporting them measured
    keeps ``gap_accounted_frac`` an identity-check (≈1.0 up to clock
    noise), with the SPLIT as the actionable number."""
    wall = side_async["wall_s"]
    window = side_async["union"]["window_s"]
    union_busy = side_async["union"]["union_busy_s"]
    tokens = side_async["tokens"]
    rate1 = side_1["tok_s"]
    busy_1r = sum(s["busy_s"] for s in side_1["busy"].values())
    ideal_wall = tokens / max(n * rate1, 1e-9)
    busy_per_tok_1r = busy_1r / max(side_1["tokens"], 1)
    ideal_busy = tokens * busy_per_tok_1r
    gap = n * max(wall - ideal_wall, 0.0)
    idle = n * max(window - union_busy, 0.0)
    overwork = n * (union_busy - ideal_busy)
    shared = n * (ideal_busy - ideal_wall)
    edge = n * max(wall - window, 0.0)
    accounted = (
        min(1.0, max(0.0, idle + overwork + shared + edge) / gap)
        if gap > 1e-9 else 1.0
    )
    return {
        "gap_s": round(gap, 3),
        "gap_idle_s": round(idle, 3),
        "gap_overwork_s": round(overwork, 3),
        "gap_shared_device_s": round(shared, 3),
        "gap_edge_s": round(edge, 3),
        "gap_accounted_frac": round(accounted, 4),
    }


def _wallclock_median(cfg, params, trace, n_replicas, slots, reps,
                      out_path=None, async_host=False):
    """``reps`` independent serves of one side; returns the run whose
    tok/s is the median, WHOLE (rate, busy, causes stay one consistent
    run — a spliced median would mix timelines). The shared noisy box
    moves single runs ±20-30%; the recorded rounds quote medians
    (``--wc-reps``), the smokes stay single-run for speed."""
    sides = [
        _wallclock_side(cfg, params, trace, n_replicas, slots,
                        out_path=(out_path if i == 0 else None),
                        async_host=async_host)
        for i in range(max(1, reps))
    ]
    sides.sort(key=lambda s: s["tok_s"])
    return sides[len(sides) // 2]


def measure_wallclock(trace=None, n_replicas: int = 2, slots: int = 4,
                      out_path: str | None = None,
                      extra_replicas=(), reps: int = 1) -> dict:
    """The ROADMAP-item-3 wall-clock fleet bench: ONE trace served by 1
    replica vs ``n_replicas``, as fast as the host can crank the loop.
    Reports aggregate tok/s both sides, per-replica device-busy
    fraction, and the bubble-cause histogram — which must account for
    >=90% of the measured 1→N efficiency gap
    (``serving_wallclock_gap_accounted_frac``; the gap in seconds is
    ``N x (wallN - tokensN / (N x rate1))``, i.e. the extra aggregate
    stream-seconds the N-replica run spent vs perfect scaling of the
    1-replica rate).

    Round 16 (the async host runtime): the bench is now a THREE-way —
    the synchronous loop keys keep their r06 meanings (the legacy
    baseline), and the ``serving_wallclock_async_*`` keys measure the
    dispatch-then-collect loop on the same trace: tok/s, efficiency,
    the decomposed gap accounting (``_async_gap_decomposition``), the
    per-replica AND union busy fractions, the bubble-cause histogram
    (worker-thread marks included), and the other-replica-tick share
    the refactor exists to shrink. ``extra_replicas`` adds compact
    sync-vs-async points at other fleet sizes
    (``serving_wallclock_r{N}_*``). ``--wc-out`` keeps the ASYNC
    N-replica run's JSONL — the surface ``ci_check.sh --async-smoke``
    replays through report/explain.

    HONESTY (``serving_wallclock_backend``): on CPU all replicas share
    one device, so N replicas CANNOT beat one — the sync bench measures
    pure host-loop serialization, and even a perfect async loop is
    floored by the shared device (the ``gap_shared_device_s`` term).
    Per-replica busy fractions under the async loop include time queued
    behind the other replica (dispatch→completion windows overlap);
    ``_union`` is true device utilization. Do not regression-gate CPU
    magnitudes; the wall-clock keys carry a wide noise band in
    ``bench_regression.py``."""
    cfg, params = _tiny_model()
    if trace is None:
        trace = default_fleet_trace()
    side_async = _wallclock_median(cfg, params, trace, n_replicas, slots,
                                   reps, out_path=out_path,
                                   async_host=True)
    side_n = _wallclock_median(cfg, params, trace, n_replicas, slots,
                               reps)
    side_1 = _wallclock_median(cfg, params, trace, 1, slots, reps)
    rate1 = side_1["tok_s"]
    rate_n = side_n["tok_s"]
    n = n_replicas
    efficiency = rate_n / max(n * rate1, 1e-9)
    # the efficiency gap in aggregate stream-seconds: how much longer
    # the N run's N streams ran vs perfect scaling of the 1r rate
    ideal_wall = side_n["tokens"] / max(n * rate1, 1e-9)
    gap_s = n * max(side_n["wall_s"] - ideal_wall, 0.0)
    bubble_s = sum(c["gap_s"] for c in side_n["causes"].values())
    accounted = (
        min(1.0, bubble_s / gap_s) if gap_s > 1e-9 else 1.0
    )
    out = {
        "serving_wallclock_backend": jax.default_backend(),
        "serving_wallclock_replicas": n,
        "serving_wallclock_trace_requests": len(trace),
        "serving_wallclock_slots_per_replica": slots,
        "serving_wallclock_tokens": side_n["tokens"],
        "serving_wallclock_wall_s_1r": round(side_1["wall_s"], 3),
        "serving_wallclock_wall_s_nr": round(side_n["wall_s"], 3),
        "serving_wallclock_tok_s_1r": round(rate1, 2),
        "serving_wallclock_tok_s_nr": round(rate_n, 2),
        "serving_wallclock_ratio_nr_over_1r": round(
            rate_n / max(rate1, 1e-9), 3
        ),
        "serving_wallclock_efficiency_frac": round(efficiency, 4),
        "serving_wallclock_gap_s": round(gap_s, 3),
        "serving_wallclock_bubble_s_total": round(bubble_s, 3),
        "serving_wallclock_bubble_over_gap": round(
            bubble_s / gap_s, 3
        ) if gap_s > 1e-9 else None,
        "serving_wallclock_gap_accounted_frac": round(accounted, 4),
        "device": str(jax.devices()[0]),
    }
    busies = []
    for rep, s in sorted(side_n["busy"].items()):
        out[f"serving_wallclock_device_busy_frac_r{rep}"] = s["busy_frac"]
        busies.append(s["busy_frac"])
    if busies:
        out["serving_wallclock_device_busy_frac_mean"] = round(
            sum(busies) / len(busies), 6
        )
    for rep, s in sorted(side_1["busy"].items()):
        out["serving_wallclock_device_busy_frac_1r"] = s["busy_frac"]
    for cause, h in sorted(side_n["causes"].items()):
        key = cause.replace("/", "_").replace("-", "_")
        out[f"serving_wallclock_bubble_{key}_s"] = round(h["gap_s"], 3)
        out[f"serving_wallclock_bubble_{key}_count"] = h["count"]
    # ---- the async host runtime side (round 16) ----
    rate_a = side_async["tok_s"]
    out["serving_wallclock_async_tokens"] = side_async["tokens"]
    out["serving_wallclock_async_wall_s_nr"] = round(
        side_async["wall_s"], 3
    )
    out["serving_wallclock_async_tok_s_nr"] = round(rate_a, 2)
    out["serving_wallclock_async_efficiency_frac"] = round(
        rate_a / max(n * rate1, 1e-9), 4
    )
    out["serving_wallclock_ratio_async_over_sync"] = round(
        rate_a / max(rate_n, 1e-9), 3
    )
    for k, v in _async_gap_decomposition(side_async, side_1, n).items():
        out[f"serving_wallclock_async_{k}"] = v
    for rep, s in sorted(side_async["busy"].items()):
        out[f"serving_wallclock_async_device_busy_frac_r{rep}"] = (
            s["busy_frac"]
        )
    out["serving_wallclock_async_device_busy_frac_union"] = (
        side_async["union"]["union_busy_frac"]
    )
    total_bubble_a = sum(
        c["gap_s"] for c in side_async["causes"].values()
    )
    other_a = side_async["causes"].get(
        "other-replica-tick", {"gap_s": 0.0}
    )["gap_s"]
    out["serving_wallclock_async_bubble_s_total"] = round(
        total_bubble_a, 3
    )
    # the acceptance headline: the sync loop attributed 96% of its
    # bubbles to other-replica-tick; the async loop must make it a
    # minority cause
    out["serving_wallclock_async_other_replica_share"] = round(
        other_a / total_bubble_a, 4
    ) if total_bubble_a > 1e-9 else 0.0
    for cause, h in sorted(side_async["causes"].items()):
        key = (cause.replace("/", "_").replace("-", "_")
               .replace("@", "_at_"))
        out[f"serving_wallclock_async_bubble_{key}_s"] = round(
            h["gap_s"], 3
        )
        out[f"serving_wallclock_async_bubble_{key}_count"] = h["count"]
    # compact sync-vs-async points at other fleet sizes (the r07
    # --wc-extra 4 point): efficiency uses the SAME 1-replica sync rate
    for m in extra_replicas:
        sa = _wallclock_median(cfg, params, trace, m, slots, reps,
                               async_host=True)
        ss = _wallclock_median(cfg, params, trace, m, slots, reps)
        p = f"serving_wallclock_r{m}"
        out[f"{p}_tok_s_sync"] = round(ss["tok_s"], 2)
        out[f"{p}_tok_s_async"] = round(sa["tok_s"], 2)
        out[f"{p}_efficiency_sync_frac"] = round(
            ss["tok_s"] / max(m * rate1, 1e-9), 4
        )
        out[f"{p}_efficiency_async_frac"] = round(
            sa["tok_s"] / max(m * rate1, 1e-9), 4
        )
        out[f"{p}_ratio_async_over_sync"] = round(
            sa["tok_s"] / max(ss["tok_s"], 1e-9), 3
        )
        out[f"{p}_device_busy_frac_union_async"] = (
            sa["union"]["union_busy_frac"]
        )
        t_b = sum(c["gap_s"] for c in sa["causes"].values())
        o_b = sa["causes"].get("other-replica-tick",
                               {"gap_s": 0.0})["gap_s"]
        out[f"{p}_async_other_replica_share"] = round(
            o_b / t_b, 4
        ) if t_b > 1e-9 else 0.0
    return out


# ---------------------------------------------------------------------------
# scale observatory soak (round 21): the ROADMAP-item-5 100k-session run
# ---------------------------------------------------------------------------


def measure_soak(requests: int = 100_000, out_path: str | None = None,
                 seed: int = 0, slots: int = 8, replicas: int = 2,
                 every_ticks: int | None = None,
                 log_max_bytes: int = 4 << 20) -> dict:
    """The scale-observatory soak (ISSUE 19 / ROADMAP item 5): stream a
    ``requests``-session heavy-tail trace — every request its OWN
    session id, the million-user shape that stresses the affinity LRU
    hardest — through a ``replicas``-replica fleet, and prove host cost
    O(live batch), not O(sessions ever):

    - the trace is NEVER materialized (``iter_trace``/``replay_stream``,
      one-request lookahead) and the router runs streaming retention
      (``retain_results=False``), so the harness itself is O(live);
    - ``ResourceMonitor`` samples RSS + mean per-tick host wall on a
      tick-count cadence into the rotating MetricsLogger JSONL
      (rotation is exercised — the per-request records alone overflow
      ``log_max_bytes`` many times over);
    - ``StructCensus`` sweeps every declared container in the fleet on
      the same cadence (undeclared containers or bound violations fail
      the run's verdict);
    - ``GrowthSentinel``/``fit_growth`` regress RSS and per-tick wall
      against cumulative sessions; slopes are quoted per 10k sessions.

    HONESTY (``serving_soak_backend``): on the shared-CPU runner the
    wall slope is a smoke alarm (neighbors steal the core; the MAD
    floor absorbs it), while the RSS slope and the census verdict are
    real host-memory claims on any backend — see ANALYSIS.md "Scale
    observatory". Profiling that is O(launches) stays OFF (no dispatch
    ledger, no reqtrace): per-tick wall comes from the monitor.
    """
    import tempfile

    from pytorch_distributed_tpu.fleet import (
        FleetRouter,
        iter_trace,
        prompt_for,
        replay_stream,
    )
    from pytorch_distributed_tpu.telemetry import (
        GrowthSentinel,
        ResourceMonitor,
        StructCensus,
        rss_mib,
        undeclared_containers,
    )
    from pytorch_distributed_tpu.utils.profiling import MetricsLogger

    cfg, params = _tiny_model()
    # Sample cadence: ~256 ticks at soak scale, scaled down for smokes
    # so short runs still give the fits >= min_samples points.
    if every_ticks is None:
        every_ticks = max(8, min(256, requests // 32))
    tmp = None
    if out_path is None:
        tmp = tempfile.TemporaryDirectory()
        out_path = os.path.join(tmp.name, "soak.jsonl")
    mlog = MetricsLogger(out_path, max_bytes=log_max_bytes)
    router = FleetRouter(
        cfg, params, n_replicas=replicas, seed=seed, metrics_log=mlog,
        n_slots=slots, block_len=16, prefill_chunk=32, admit_per_step=8,
        retain_results=False, prefix_cache=True,
    )
    router.warmup()
    census = StructCensus(mlog)
    census.register_many(router.census_owners())
    monitor = ResourceMonitor(mlog, every_ticks=every_ticks,
                              gc_objects=True, tracemalloc_every=32,
                              top_sites=5)
    census.register("monitor", monitor)
    sentinel = GrowthSentinel()
    census.register("sentinel", sentinel)
    undeclared_at_start = sorted(
        u for name, obj in census.owners()
        for u in undeclared_containers(obj))
    rss0, rss_src = rss_mib()

    submitted = [0]
    peak_live = [0]
    worst = [0.0, ""]  # max worst_ratio across sweeps + its structure

    def submit(r):
        router.submit(prompt_for(r, cfg.vocab_size), r.max_new,
                      session=r.session)
        submitted[0] += 1

    def tick():
        t0 = time.perf_counter()
        router.step()
        dt = time.perf_counter() - t0
        live = router.live_requests()
        if live > peak_live[0]:
            peak_live[0] = live
        rec = monitor.tick(live=live, cumulative=submitted[0], wall_s=dt)
        if rec is not None:
            sweep = census.sweep(live=live, replicas=replicas,
                                 tick=monitor.ticks, live_slack=4 * slots)
            # The observatory's own rings (monitor history, sentinel
            # series) grow by construction until their caps fill; the
            # census audits those caps. Size-growth flags are for the
            # FLEET's structures.
            sentinel.observe_sizes(submitted[0], {
                k: v for k, v in sweep["structures"].items()
                if not k.startswith(("monitor.", "sentinel."))})
            if sweep["worst_ratio"] > worst[0]:
                worst[0], worst[1] = sweep["worst_ratio"], sweep["worst_name"]

    # Offered load ~1.6 req/tick against ~2.3 req/tick of fleet service
    # capacity (ceil(prompt/chunk) + max_new slot-ticks per request):
    # heavily loaded, never divergent. duration_s is an over-generous
    # horizon; islice ends the stream at exactly ``requests``.
    import itertools

    arrivals = itertools.islice(
        iter_trace(seed=seed, duration_s=1e12, base_rate=2.0,
                   burst_rate_mult=4.0, burst_every_s=40.0,
                   burst_len_s=6.0, prompt_median=16, prompt_max=64,
                   max_new_median=6, max_new_max=12,
                   unique_sessions=True),
        requests,
    )
    t_start = time.perf_counter()
    ticks = replay_stream(arrivals, submit, tick,
                          lambda: router.idle, tick_s=0.6)
    wall = time.perf_counter() - t_start
    final = monitor.sample(live=router.live_requests(),
                           cumulative=submitted[0])
    census.sweep(live=router.live_requests(), replicas=replicas,
                 tick=monitor.ticks, live_slack=4 * slots)
    m = router.metrics()
    mlog.close()
    monitor.close()

    # Growth fits against cumulative sessions. RSS gets a tight relative
    # floor (0.5% of the level — the jax runtime's ~1 GiB baseline would
    # otherwise hide tens of MiB of leak behind the default 5%); the
    # shared-CPU wall series keeps the default.
    from pytorch_distributed_tpu.telemetry import fit_growth

    rss_fit = fit_growth(*monitor.rss_series(), rel_floor=0.005,
                         abs_floor=1.0)
    wall_fit = fit_growth(*monitor.wall_series(), abs_floor=0.05)
    out = {
        "serving_soak_backend": jax.default_backend(),
        "serving_soak_sessions": submitted[0],
        "serving_soak_completed": m["completed"],
        "serving_soak_shed": m["shed"],
        "serving_soak_ticks": ticks,
        "serving_soak_wall_s": round(wall, 1),
        "serving_soak_rss_source": rss_src,
        "serving_soak_rss_mib_start": round(rss0, 1),
        "serving_soak_rss_mib_final": round(final["rss_mib"], 1),
        "serving_soak_rss_slope_mib_per_10k": round(
            rss_fit["slope"] * 1e4, 3),
        "serving_soak_rss_verdict": rss_fit["verdict"],
        "serving_soak_host_wall_slope_ms_per_10k": round(
            wall_fit["slope"] * 1e4, 4),
        "serving_soak_host_wall_verdict": wall_fit["verdict"],
        "serving_soak_census_sweeps": census.sweeps,
        "serving_soak_census_violations": census.total_violations,
        "serving_soak_census_undeclared": census.total_undeclared,
        "serving_soak_census_verdict": census.verdict(),
        "serving_soak_census_worst_frac": round(worst[0], 4),
        "serving_soak_census_worst_name": worst[1],
        "serving_soak_undeclared_at_start": len(undeclared_at_start),
        "serving_soak_size_flags": ",".join(
            f for f in sentinel.flags()) or "none",
        "serving_soak_peak_live": peak_live[0],
        "serving_soak_results_dropped": m["results_dropped"],
        "serving_soak_rotations": mlog.rotations,
        "serving_soak_tokens_out": m["tokens_out"],
        "serving_soak_tokens_per_s": round(
            m["tokens_out"] / max(wall, 1e-9), 1),
        "device": str(jax.devices()[0]),
    }
    if tmp is not None:
        tmp.cleanup()
    return out


def measure_http(requests: int = 48, seed: int = 0, slots: int = 4,
                 replicas: int = 2, disconnect_every: int = 6,
                 max_conc: int = 8, time_scale: float = 0.05,
                 out_path: str | None = None) -> dict:
    """The HTTP front door measured OVER THE WIRE (ISSUE 20): a real
    socket per request against ``gateway.Gateway`` on an ephemeral
    port, paced by the stock bursty trace (time-scaled so the bench
    stays in seconds). Every ``disconnect_every``-th request hangs up
    after its first token — the disconnect→cancel path is part of the
    steady state being measured, not a separate scenario.

    Reports what in-process benches cannot see: TTFT measured at the
    socket (``serving_http_ttft_wire_*`` — admission + first decode +
    serialization + kernel send), the inter-token stream gap p95 (the
    SSE jitter a client actually experiences), the 429 shed rate at
    the door, and the cancel-to-block-free latency (socket close →
    ``FleetRouter.cancel`` freed the KV blocks).

    HONESTY (``serving_http_backend``): loopback TCP on a shared CPU
    host — wire latencies carry the host's scheduler noise and a tiny
    model's decode rate; magnitudes are structural (is TTFT dominated
    by queueing? do gaps spike at bursts?), not device claims.
    """
    import itertools
    import tempfile
    import threading

    from pytorch_distributed_tpu.fleet import (
        FleetRouter,
        iter_trace,
        prompt_for,
    )
    from pytorch_distributed_tpu.gateway import (
        Gateway,
        generate,
        open_stream,
    )
    from pytorch_distributed_tpu.utils.profiling import MetricsLogger

    cfg, params = _tiny_model()
    tmp = None
    if out_path is None:
        tmp = tempfile.TemporaryDirectory()
        out_path = os.path.join(tmp.name, "http.jsonl")
    mlog = MetricsLogger(out_path)
    router = FleetRouter(
        cfg, params, n_replicas=replicas, seed=seed, metrics_log=mlog,
        n_slots=slots, block_len=16, prefill_chunk=32,
        retain_results=False, async_host=True,
    )
    router.warmup()
    gw = Gateway(router, port=0, metrics_log=mlog)
    gw.start()
    base = f"http://127.0.0.1:{gw.port}"

    trace = list(itertools.islice(
        iter_trace(seed=seed, duration_s=1e12, base_rate=2.0,
                   burst_rate_mult=4.0, burst_every_s=40.0,
                   burst_len_s=6.0, prompt_median=16, prompt_max=64,
                   max_new_median=6, max_new_max=12,
                   unique_sessions=True),
        requests,
    ))
    statuses: list = []
    disconnects = [0]
    gate = threading.Semaphore(max_conc)
    lock = threading.Lock()

    def run_one(i, req, t_start):
        # pace to the (scaled) trace arrival, bounded concurrency
        delay = req.t * time_scale - (time.perf_counter() - t_start)
        if delay > 0:
            time.sleep(delay)
        prompt = prompt_for(req, cfg.vocab_size, seed=seed)
        with gate:
            if disconnect_every and i % disconnect_every == \
                    disconnect_every - 1:
                try:
                    st = open_stream(base, prompt, req.max_new,
                                     session=req.session, timeout=60.0)
                    next(st.events())
                    st.close()
                    with lock:
                        statuses.append(200)
                        disconnects[0] += 1
                except Exception:
                    with lock:
                        statuses.append(-1)
                return
            out = generate(base, prompt, req.max_new,
                           session=req.session, timeout=60.0)
            with lock:
                statuses.append(out["status"])

    t_start = time.perf_counter()
    threads = [threading.Thread(target=run_one, args=(i, r, t_start),
                                daemon=True)
               for i, r in enumerate(trace)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300.0)
    wall = time.perf_counter() - t_start
    gm = gw.metrics()
    gw.stop()
    router.drain(max_steps=20_000)
    m = router.metrics()
    mlog.close()

    served = sum(1 for s in statuses if s == 200)
    shed = sum(1 for s in statuses if s == 429)
    out = {
        "serving_http_backend": jax.default_backend(),
        "serving_http_requests": len(statuses),
        "serving_http_served": served,
        "serving_http_shed": shed,
        "serving_http_429_rate": round(shed / max(len(statuses), 1), 4),
        "serving_http_errors": sum(1 for s in statuses
                                   if s not in (200, 429)),
        "serving_http_disconnects": disconnects[0],
        "serving_http_cancelled": m["cancelled"],
        "serving_http_wall_s": round(wall, 2),
        "serving_http_tokens_out": m["tokens_out"],
        "serving_http_ttft_wire_p50_ms": round(
            gm.get("gateway_ttft_wire_p50_s", 0.0) * 1e3, 2),
        "serving_http_ttft_wire_p95_ms": round(
            gm.get("gateway_ttft_wire_p95_s", 0.0) * 1e3, 2),
        "serving_http_gap_p95_ms": round(
            gm.get("gateway_gap_p95_s", 0.0) * 1e3, 2),
        "serving_http_worst_gap_ms": gm.get("gateway_worst_gap_ms", 0.0),
        "serving_http_cancel_free_p95_ms": round(
            gm.get("gateway_cancel_free_p95_s", 0.0) * 1e3, 2),
        "serving_http_bytes_out": gm.get("gateway_bytes_out", 0),
        "device": str(jax.devices()[0]),
    }
    if tmp is not None:
        tmp.cleanup()
    return out


def link_probe(mb: int = 16, reps: int = 5) -> dict:
    """Same-run bandwidth/link probe, co-quoted with every serving bench
    row (ISSUE 8, ADVICE §6 — the ckpt bench's same-minute disk-probe
    pattern applied to serving): cross-day serving swings on a tunneled
    runtime track the LINK and the shared host, not the engine, so each
    row carries the medium it was measured through.

    Three rates, median of ``reps``: host memcpy (the shared-box
    contention proxy — the round-5 stall transients were pure user-time
    memcpy slowdowns), host→device put, and device→host get of the same
    buffer (the ~24 MB/s tunnel hazard PERF_NOTES §1 documents)."""
    import numpy as np

    buf = np.ones(mb * 2**20, np.uint8)

    def med(f):
        times = []
        for _ in range(reps):
            t0 = time.perf_counter()
            f()
            times.append(time.perf_counter() - t0)
        return float(np.median(times))

    host_s = med(lambda: buf.copy())
    dev = None

    def h2d():
        nonlocal dev
        dev = jax.block_until_ready(jax.device_put(buf))

    h2d_s = med(h2d)
    d2h_s = med(lambda: np.asarray(jax.device_get(dev)))
    return {
        "probe_mb": mb,
        "probe_host_memcpy_mb_s": round(mb / host_s, 1),
        "probe_h2d_mb_s": round(mb / h2d_s, 1),
        "probe_d2h_mb_s": round(mb / d2h_s, 1),
    }


def _argval(flag: str, default, cast=float):
    if flag in sys.argv:
        return cast(sys.argv[sys.argv.index(flag) + 1])
    return default


def _cli_trace():
    """--trace PATH → loaded trace (or None)."""
    path = _argval("--trace", None, str)
    if path is None:
        return None
    from pytorch_distributed_tpu.fleet import load_trace

    return load_trace(path)


def main() -> None:
    slots = 32
    if "--slots" in sys.argv:
        slots = int(sys.argv[sys.argv.index("--slots") + 1])
    if "--gen-trace" in sys.argv:
        from pytorch_distributed_tpu.fleet import generate_trace, save_trace

        path = sys.argv[sys.argv.index("--gen-trace") + 1]
        heavy = "--trace-prefill-heavy" in sys.argv
        kw = dict(
            seed=_argval("--trace-seed", 0, int),
            duration_s=_argval("--trace-duration", 240.0),
            base_rate=_argval("--trace-base-rate", 0.32),
            burst_rate_mult=_argval("--trace-burst-mult", 4.0),
            burst_every_s=_argval("--trace-burst-every", 40.0),
            burst_len_s=_argval("--trace-burst-len", 6.0),
            sessions=_argval("--trace-sessions", 16, int),
            prompt_median=_argval("--trace-prompt-median",
                                  48 if heavy else 24, int),
            prompt_max=_argval("--trace-prompt-max", 96, int),
            max_new_median=_argval("--trace-max-new-median",
                                   6 if heavy else 12, int),
            max_new_max=_argval("--trace-max-new-max", 24, int),
        )
        trace = generate_trace(**kw)
        save_trace(path, trace, **kw)
        print(json.dumps({"trace_path": path, "requests": len(trace), **kw}))
        return
    # same-run link probe co-quoted with every measured row (ADVICE §6):
    # a cross-day swing in any serving number below is attributable —
    # either the probes moved with it (environment weather) or they
    # didn't (a real engine change)
    probe = link_probe()
    if "--fleet" in sys.argv:
        print(json.dumps({**measure_fleet(
            trace=_cli_trace(),
            slo_ttft_ticks=_argval("--slo-ttft-ticks", None),
        ), **probe}))
        return
    if "--disagg" in sys.argv:
        print(json.dumps({**measure_disagg(trace=_cli_trace()), **probe}))
        return
    if "--wall-clock" in sys.argv:
        extra = _argval("--wc-extra", "", str)
        print(json.dumps({**measure_wallclock(
            trace=_cli_trace(),
            n_replicas=_argval("--wc-replicas", 2, int),
            slots=_argval("--wc-slots", 4, int),
            out_path=_argval("--wc-out", None, str),
            extra_replicas=tuple(
                int(x) for x in extra.split(",") if x.strip()
            ),
            reps=_argval("--wc-reps", 1, int),
        ), **probe}))
        return
    if "--prefix" in sys.argv:
        print(json.dumps({**measure_prefix(
            trace=_cli_trace(),
            slots=_argval("--prefix-slots", 8, int),
            prefix_len=_argval("--prefix-len", 64, int),
            replicas=_argval("--prefix-replicas", 2, int),
            out_path=_argval("--prefix-out", None, str),
        ), **probe}))
        return
    if "--soak" in sys.argv:
        print(json.dumps({**measure_soak(
            requests=_argval("--soak-requests", 100_000, int),
            out_path=_argval("--soak-log", None, str),
            slots=_argval("--soak-slots", 8, int),
            replicas=_argval("--soak-replicas", 2, int),
            every_ticks=_argval("--soak-every", None, int),
            log_max_bytes=int(_argval("--soak-log-mb", 4.0) * 2**20),
        ), **probe}))
        return
    if "--http" in sys.argv:
        print(json.dumps({**measure_http(
            requests=_argval("--http-requests", 48, int),
            slots=_argval("--http-slots", 4, int),
            replicas=_argval("--http-replicas", 2, int),
            disconnect_every=_argval("--http-disconnect-every", 6, int),
            out_path=_argval("--http-out", None, str),
        ), **probe}))
        return
    if "--pressure" in sys.argv:
        print(json.dumps({**measure_pressure(
            trace=_cli_trace(),
            slots=_argval("--pressure-slots", 4, int),
            n_blocks=_argval("--pressure-blocks", 13, int),
            sessions=_argval("--pressure-sessions", 100_000, int),
            duration_s=_argval("--pressure-duration", 90.0),
        ), **probe}))
        return
    if "--stall" in sys.argv:
        print(json.dumps({**measure_admission_stall(slots), **probe}))
        return
    if "--paged-stall" in sys.argv:
        print(json.dumps({**measure_paged_admission(slots), **probe}))
        return
    if "--paged-latency" in sys.argv:
        print(json.dumps({**measure_paged_latency(trace=_cli_trace()),
                          **probe}))
        return
    if "--gather-ab" in sys.argv:
        print(json.dumps({**measure_gather_ab(
            slots=_argval("--ab-slots", 8, int),
            ticks=_argval("--ab-ticks", 32, int),
            prompt_len=_argval("--ab-prompt-len", 64, int),
            tiny="--tiny" in sys.argv,
            tuned_dir=(_argval("--autotune-dir", None, str)
                       if "--tuned" in sys.argv else None),
        ), **probe}))
        return
    if "--tp-virtual" in sys.argv:
        print(json.dumps({**measure_tp_virtual(), **probe}))
        return
    print(json.dumps({**measure(slots), **probe}))


if __name__ == "__main__":
    main()
