"""Render telemetry JSONL into the summary table bench.py consumes.

Reads one or more ``MetricsLogger`` JSONL streams (a training run's
``metrics.jsonl``, a serving run's ``--metrics-out`` file, or both) and
produces, from the JSONL alone:

- the **goodput breakdown** of a training run — productive / compile /
  data-wait / checkpoint / rollback / stall fractions (summing to 1)
  from the ``kind="goodput"`` record, plus the train-series shape
  (steps logged, final loss) and epoch timing;
- **serving latency percentiles** — TTFT and per-output-token p50/p95
  (and queue wait) recomputed exactly from the per-request
  ``kind="request"`` records (falling back to the
  ``kind="serving_summary"`` percentiles when only the summary was
  kept);
- the **fleet section** (round 10; ``fleet/``) — per-replica
  TTFT/queue-wait p50/p95/p99, shed rate (explicit rejects with
  reasons), spill rate (requests routed off their affinity replica),
  and handoff counts, from the same ``kind="request"`` records (which
  carry ``replica_id``/``rejected``/``reject_reason``/``spilled``) plus
  the ``kind="fleet_summary"`` rollup;
- the **cost/roofline table** (round 11; ``telemetry/costmodel.py``) —
  one row per program from ``kind="program_cost"`` records: calls, mean
  ms, achieved GFLOP/s and GB/s, arithmetic intensity, MFU and the
  compute-vs-bandwidth bound (ceiling columns render "-" when no device
  ceiling is known; set PDT_PEAK_FLOPS / PDT_PEAK_GBS);
- the **anomaly section** (round 11; ``telemetry/anomaly.py``) — count
  per series plus the latest excursions with their z-scores, from
  ``kind="anomaly"`` records;
- the **pressure section** (round 13; KV offload + preemption) —
  preempt rate, per-direction swap p50/p95 and bytes moved, swap-vs-
  recompute decision counts and the predicted-cost crossover histogram,
  from ``kind="preempt"``/``kind="swap"`` records;
- the **prefix section** (round 17; prefix-sharing KV cache) — hit
  rate, covered-prefix fraction, shared-blocks-per-hit percentiles,
  COW copies and admission-path evictions, from ``kind="prefix"``
  per-admission records plus the fleet rollup;
- the **overlap section** (round 15; ``telemetry/overlap.py``) —
  per-replica device-busy fraction, the bubble-cause histogram
  (other-replica-tick / tokenize / admission / JSONL / handoff / swap /
  idle), and dispatch-to-completion p50/p95 per program, from
  ``kind="overlap"`` dispatch-ledger records;
- the **host-resource section** (round 21; ``telemetry/hostprof.py``)
  — RSS and per-tick host-wall growth fits against cumulative sessions
  (slopes per 10k, flat/linear/superlinear verdicts), gc population and
  tracemalloc top sites, from ``kind="resource"`` monitor samples;
- the **structure-census section** (round 21; ``telemetry/census.py``)
  — sweep totals, bound violations and undeclared containers (both
  failures), worst bound ratio, and peak structure sizes, from
  ``kind="census"`` sweep records;
- the **http-ingress section** (round 22; ``gateway/server.py``) — one
  record per ``/v1/generate`` connection: status histogram (200 served
  / 429 shed / 400 malformed), disconnect→cancel counts,
  over-the-wire TTFT percentiles, bytes out and the worst inter-token
  stream gap, from ``kind="http"`` records;
- the **request-trace section** (round 14; ``telemetry/reqtrace.py``) —
  lifecycle trace counts, completeness (every span closed, parents
  acyclic), open spans, and phase totals from ``kind="span"`` records
  (``scripts/explain_request.py`` reconstructs any single rid).

Usage:
    python scripts/telemetry_report.py RUN.jsonl [SERVE.jsonl ...] [--json]

Human-readable tables by default; ``--json`` appends one flat JSON dict
(bench.py record style) as the last line. Exits non-zero if NO goodput
record and NO serving latencies were found — the ci_check.sh
``--telemetry-smoke`` gate.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from pytorch_distributed_tpu.telemetry.goodput import (  # noqa: E402
    GOODPUT_CATEGORIES,
)
from pytorch_distributed_tpu.telemetry.latency import (  # noqa: E402
    percentiles,
)


def load_records(paths: List[str]) -> List[dict]:
    records = []
    for path in paths:
        with open(path) as f:
            for i, line in enumerate(f):
                line = line.strip()
                if not line:
                    continue
                try:
                    records.append(json.loads(line))
                except json.JSONDecodeError as e:
                    raise SystemExit(
                        f"{path}:{i + 1}: not JSONL ({e})"
                    ) from e
    return records


def _fmt_row(label: str, *cells) -> str:
    return "  " + label.ljust(20) + "".join(str(c).rjust(16) for c in cells)


def goodput_section(records: List[dict], out: dict) -> List[str]:
    """Goodput breakdown from the newest ``kind="goodput"`` record."""
    gps = [r for r in records if r.get("kind") == "goodput"]
    if not gps:
        return []
    gp = gps[-1]  # the run's final (cumulative) ledger report
    lines = ["== goodput =="]
    lines.append(_fmt_row("category", "seconds", "fraction"))
    total_frac = gp["goodput_frac"]
    lines.append(_fmt_row(
        "productive", f"{gp['productive_s']:.2f}",
        f"{gp['goodput_frac']:.3f}",
    ))
    for cat in GOODPUT_CATEGORIES:
        # .get: records written before a category existed (e.g. "trace",
        # added with compilecache/) render as zero rather than erroring
        total_frac += gp.get(f"{cat}_frac", 0.0)
        lines.append(_fmt_row(
            cat, f"{gp.get(f'{cat}_s', 0.0):.2f}",
            f"{gp.get(f'{cat}_frac', 0.0):.3f}"
        ))
    lines.append(_fmt_row("wall", f"{gp['wall_s']:.2f}",
                          f"{total_frac:.3f}"))
    out["goodput_frac"] = round(gp["goodput_frac"], 4)
    out["goodput_wall_s"] = round(gp["wall_s"], 2)
    for cat in GOODPUT_CATEGORIES:
        out[f"goodput_{cat}_frac"] = round(gp.get(f"{cat}_frac", 0.0), 4)
    return lines


def train_section(records: List[dict], out: dict) -> List[str]:
    trains = [r for r in records if r.get("kind") == "train"]
    epochs = [r for r in records if r.get("kind") == "epoch_timing"]
    if not trains and not epochs:
        return []
    lines = ["== training =="]
    if trains:
        last = trains[-1]
        lines.append(
            f"  {len(trains)} log events; last: epoch {last.get('epoch')} "
            f"step {last.get('step')} loss {last.get('loss', float('nan')):.4f}"
        )
        out["train_log_events"] = len(trains)
        out["train_last_loss"] = last.get("loss")
    for r in epochs:
        rate = r.get("tokens_per_s") or r.get("items_per_s")
        rate_s = f", {rate:.0f}/s" if rate else ""
        lines.append(
            f"  epoch {r['epoch']}: {r['steps']} steps, "
            f"{r['mean_ms']:.1f} ms/step{rate_s}"
        )
    if epochs:
        out["train_mean_step_ms"] = round(epochs[-1]["mean_ms"], 2)
    return lines


def warmup_section(records: List[dict], out: dict) -> List[str]:
    """Warmup manifest (``kind="warmup"`` from compilecache.WarmupRunner):
    how many programs compiled ahead of traffic, how many were
    persistent-cache hits, and the XLA-backend share of the time — the
    cold-vs-warm start comparison surface."""
    warms = [r for r in records if r.get("kind") == "warmup"]
    if not warms:
        return []
    hits = sum(1 for r in warms if r.get("cache_hit"))
    total = sum(r.get("seconds", 0.0) for r in warms)
    backend = sum(r.get("backend_compile_s", 0.0) for r in warms)
    lines = ["== warmup =="]
    lines.append(
        f"  {len(warms)} programs in {total:.2f}s "
        f"({hits} cache hits, {len(warms) - hits} fresh; "
        f"backend compile {backend:.2f}s)"
    )
    slowest = max(warms, key=lambda r: r.get("seconds", 0.0))
    lines.append(
        f"  slowest: {slowest.get('program')} "
        f"{slowest.get('seconds', 0.0):.2f}s"
        f"{' (hit)' if slowest.get('cache_hit') else ''}"
    )
    out["warmup_programs"] = len(warms)
    out["warmup_cache_hits"] = hits
    out["warmup_total_s"] = round(total, 3)
    out["warmup_backend_compile_s"] = round(backend, 3)
    return lines


def serving_section(records: List[dict], out: dict) -> List[str]:
    reqs = [r for r in records if r.get("kind") == "request"]
    summaries = [r for r in records if r.get("kind") == "serving_summary"]
    if not reqs and not summaries:
        return []
    lines = ["== serving latency =="]
    if reqs:
        # exact recomputation from the raw per-request records
        ttft = [r["ttft_s"] for r in reqs if "ttft_s" in r]
        # warm-only TTFT: requests whose lifetime saw no compile stall
        # (cold=False; records predating the flag count as warm) — the
        # honest SLO series a cold first-bucket request would pollute
        ttft_warm = [r["ttft_s"] for r in reqs
                     if "ttft_s" in r and not r.get("cold")]
        cold = sum(1 for r in reqs if r.get("cold"))
        queue = [r["queue_wait_s"] for r in reqs if "queue_wait_s" in r]
        gaps = [g for r in reqs for g in r.get("token_gaps_s", [])]
        lines.append(
            f"  {len(reqs)} requests ({cold} cold), "
            f"{sum(r.get('new_tokens', 0) for r in reqs)} tokens"
        )
        out["serving_requests"] = len(reqs)
        out["serving_cold_requests"] = cold
        for name, vals in (("ttft", ttft), ("ttft_warm", ttft_warm),
                           ("token_lat", gaps), ("queue_wait", queue)):
            ps = percentiles(vals, qs=(50, 95))
            if not ps:
                continue
            lines.append(_fmt_row(
                name,
                f"p50 {ps['p50'] * 1e3:.1f}ms",
                f"p95 {ps['p95'] * 1e3:.1f}ms",
            ))
            out[f"serving_{name}_p50_ms"] = round(ps["p50"] * 1e3, 3)
            out[f"serving_{name}_p95_ms"] = round(ps["p95"] * 1e3, 3)
    elif summaries:
        s = summaries[-1]
        for name in ("ttft", "token_lat", "queue_wait"):
            p50, p95 = s.get(f"{name}_p50_s"), s.get(f"{name}_p95_s")
            if p50 is None:
                continue
            lines.append(_fmt_row(
                name, f"p50 {p50 * 1e3:.1f}ms", f"p95 {p95 * 1e3:.1f}ms"
            ))
            out[f"serving_{name}_p50_ms"] = round(p50 * 1e3, 3)
            out[f"serving_{name}_p95_ms"] = round(p95 * 1e3, 3)
    if summaries:
        s = summaries[-1]
        for k in ("tokens_per_s", "occupancy_mean", "padding_waste_frac"):
            if k in s:
                out[f"serving_{k}"] = round(float(s[k]), 4)
    return lines


def fleet_section(records: List[dict], out: dict) -> List[str]:
    """Per-replica latency percentiles + shed/spill accounting from the
    fleet-stamped request records (``replica_id`` present since round
    10) and the ``kind="fleet_summary"`` rollup."""
    reqs = [r for r in records
            if r.get("kind") == "request" and "replica_id" in r]
    summaries = [r for r in records if r.get("kind") == "fleet_summary"]
    if not reqs and not summaries:
        return []
    lines = ["== fleet =="]
    served = [r for r in reqs if not r.get("rejected")]
    shed = [r for r in reqs if r.get("rejected")]
    spilled = sum(1 for r in served if r.get("spilled"))
    by_rep: dict = {}
    for r in served:
        by_rep.setdefault(r["replica_id"], []).append(r)
    out["fleet_replicas"] = len(by_rep)
    out["fleet_requests"] = len(reqs)
    out["fleet_shed"] = len(shed)
    out["fleet_shed_rate"] = (
        round(len(shed) / len(reqs), 4) if reqs else 0.0
    )
    out["fleet_spill_rate"] = (
        round(spilled / len(served), 4) if served else 0.0
    )
    lines.append(
        f"  {len(reqs)} requests over {len(by_rep)} replica(s); "
        f"shed {len(shed)} ({out['fleet_shed_rate']:.1%}), "
        f"spilled {spilled} ({out['fleet_spill_rate']:.1%})"
    )
    if shed:
        reasons: dict = {}
        for r in shed:
            reasons[r.get("reject_reason", "?")] = (
                reasons.get(r.get("reject_reason", "?"), 0) + 1
            )
        lines.append("  shed reasons: " + ", ".join(
            f"{k}={v}" for k, v in sorted(reasons.items())
        ))
    for rep_id, rs in sorted(by_rep.items()):
        cells = [f"{len(rs)} reqs"]
        for name, key in (("ttft", "ttft_s"), ("queue", "queue_wait_s")):
            ps = percentiles([r[key] for r in rs if key in r])
            if not ps:
                continue
            cells.append(
                f"{name} " + "/".join(
                    f"{ps[q] * 1e3:.1f}" for q in ("p50", "p95", "p99")
                ) + "ms"
            )
            for q in ("p50", "p95", "p99"):
                out[f"fleet_r{rep_id}_{name}_{q}_ms"] = round(
                    ps[q] * 1e3, 3
                )
        lines.append("  " + f"replica {rep_id}".ljust(12)
                     + "  ".join(str(c).rjust(30) for c in cells))
    if summaries:
        s = summaries[-1]
        for k in ("handoffs", "recommended_replicas_peak", "replicas",
                  "disaggregated"):
            if k in s:
                out[f"fleet_{k}"] = s[k]
        if s.get("handoffs"):
            lines.append(
                f"  {s['handoffs']} prefill→decode handoffs"
                + (f", mean {s['handoff_mean_s'] * 1e3:.2f}ms"
                   if "handoff_mean_s" in s else "")
            )
    return lines


def cost_section(records: List[dict], out: dict) -> List[str]:
    """Per-program MFU/roofline table from ``kind="program_cost"``
    records (newest record per program wins — a rerun's cards supersede
    the first run's). The in-runtime generalization of the one-off
    ``scripts/exp_resnet_roofline.py`` table."""
    cards: dict = {}
    for r in records:
        if r.get("kind") == "program_cost":
            cards[r["program"]] = r  # newest wins
    if not cards:
        return []

    def fmt(v, scale=1.0, digits=1):
        return f"{v / scale:.{digits}f}" if v is not None else "-"

    lines = ["== program cost / roofline =="]
    lines.append(_fmt_row(
        "program", "calls", "mean_ms", "GFLOP/s", "GB/s", "F/B", "MFU",
        "bound", "cfg",
    ))
    measured = 0
    # measured programs first (by total time, attribution order), then
    # the cold remainder alphabetically
    ordered = sorted(
        cards.values(),
        key=lambda r: (-(r.get("total_s") or 0.0), r["program"]),
    )
    for r in ordered:
        if r.get("calls"):
            measured += 1
        lines.append(_fmt_row(
            r["program"][:20],
            r.get("calls", 0),
            fmt(r.get("mean_s"), 1e-3, 3) if r.get("calls") else "-",
            fmt(r.get("achieved_flops_s"), 1e9),
            fmt(r.get("achieved_bytes_s"), 1e9),
            fmt(r.get("intensity_flop_b"), 1.0),
            f"{r['mfu']:.4f}" if r.get("mfu") is not None else "-",
            r.get("bound", "-"),
            # round-20 tuned-config provenance (the scheduler annotates
            # every card): which kernel config actually served
            ("tuned" if r.get("tuned")
             else "default" if "tuned" in r else "-"),
        ))
    # one provenance trailer when any card carries the annotation: the
    # applied knobs + whether the tuned file's fingerprint matched
    tuned_rows = [r for r in cards.values() if "tuned" in r]
    if tuned_rows:
        t = tuned_rows[0]
        state = ("tuned, fingerprint match" if t.get("tuned_match")
                 else "tuned" if t.get("tuned")
                 else "default (no tuned config"
                      + (" matched)" if t.get("tuned_fingerprint")
                         else " dir)"))
        lines.append(
            f"kernel config: {state} — block_len="
            f"{t.get('tuned_block_len', '-')} prefill_chunk="
            f"{t.get('tuned_prefill_chunk', '-')} split_s="
            f"{t.get('tuned_split_s')}"
        )
        out["cost_tuned"] = bool(t.get("tuned"))
    out["cost_programs"] = len(cards)
    out["cost_measured_programs"] = measured
    mfus = [r["mfu"] for r in cards.values() if r.get("mfu") is not None]
    if mfus:
        out["cost_mfu_max"] = round(max(mfus), 5)
    bw = [r for r in cards.values() if r.get("bound") == "bandwidth"]
    if any("bound" in r for r in cards.values()):
        out["cost_bandwidth_bound"] = len(bw)
    return lines


def pressure_section(records: List[dict], out: dict) -> List[str]:
    """KV pressure tier (round 13; ``serving/`` offload + preemption):
    preempt rate, swap walls, and the swap-vs-recompute decision
    crossover, from ``kind="preempt"`` / ``kind="swap"`` records."""
    preempts = [r for r in records if r.get("kind") == "preempt"]
    swaps = [r for r in records if r.get("kind") == "swap"]
    if not preempts and not swaps:
        return []
    lines = ["== kv pressure =="]
    reqs = [r for r in records
            if r.get("kind") == "request" and not r.get("rejected")]
    rate = len(preempts) / len(reqs) if reqs else 0.0
    by_choice: dict = {}
    for r in preempts:
        by_choice[r.get("decision", "?")] = (
            by_choice.get(r.get("decision", "?"), 0) + 1
        )
    lines.append(
        f"  {len(preempts)} preemptions"
        + (f" over {len(reqs)} requests ({rate:.1%})" if reqs else "")
        + "; decisions: " + ", ".join(
            f"{k}={v}" for k, v in sorted(by_choice.items())
        )
    )
    out["pressure_preempts"] = len(preempts)
    out["pressure_preempt_rate"] = round(rate, 4)
    out["pressure_decision_swap"] = by_choice.get("swap", 0)
    out["pressure_decision_recompute"] = by_choice.get("recompute", 0)
    ok = [r for r in swaps if r.get("ok")]
    fails = [r for r in swaps if not r.get("ok")]
    out["pressure_swap_aborts"] = len(fails)
    for direction in ("out", "in"):
        walls = [r["wall_s"] for r in ok
                 if r.get("direction") == direction and "wall_s" in r]
        if not walls:
            continue
        ps = percentiles(walls, qs=(50, 95))
        moved = sum(r.get("bytes", 0) for r in ok
                    if r.get("direction") == direction)
        lines.append(_fmt_row(
            f"swap_{direction}", f"{len(walls)}x",
            f"p50 {ps['p50'] * 1e3:.2f}ms",
            f"p95 {ps['p95'] * 1e3:.2f}ms",
            f"{moved / 2**20:.2f}MiB",
        ))
        out[f"pressure_swap_{direction}_p95_ms"] = round(
            ps["p95"] * 1e3, 3
        )
        out[f"pressure_swap_{direction}_bytes"] = moved
    # decision-crossover histogram: predicted swap/recompute cost ratio
    # per preemption, bucketed in octaves around the crossover at 1 —
    # shows WHERE on the curve this workload's preemptions landed
    ratios = [
        r["predicted_swap_s"] / r["predicted_recompute_s"]
        for r in preempts
        if r.get("predicted_swap_s") and r.get("predicted_recompute_s")
    ]
    if ratios:
        edges = (0.25, 0.5, 1.0, 2.0, 4.0)
        labels = ["<1/4x", "1/4-1/2x", "1/2-1x", "1-2x", "2-4x", ">4x"]
        counts = [0] * (len(edges) + 1)
        for v in ratios:
            i = sum(v >= e for e in edges)
            counts[i] += 1
        lines.append("  swap/recompute predicted-cost crossover: "
                     + ", ".join(f"{l}={c}" for l, c in
                                 zip(labels, counts) if c))
        for l, c in zip(labels, counts):
            out[f"pressure_crossover_{l}"] = c
    return lines


def prefix_section(records: List[dict], out: dict) -> List[str]:
    """Prefix cache (round 17; ``serving/`` radix reuse + COW): hit
    rate, covered-prefix fraction, sharing/COW/eviction totals, from
    ``kind="prefix"`` per-admission records plus the fleet/serving
    summary rollups."""
    recs = [r for r in records if r.get("kind") == "prefix"]
    if not recs:
        return []
    lines = ["== prefix cache =="]
    hits = [r for r in recs if r.get("covered", 0) > 0]
    covered = sum(r.get("covered", 0) for r in recs)
    prompt = sum(r.get("prompt_len", 0) for r in recs)
    cows = sum(1 for r in recs if r.get("cow"))
    evicted = sum(r.get("evicted", 0) for r in recs)
    lines.append(
        f"  {len(recs)} prefix admissions, {len(hits)} hits "
        f"({len(hits) / len(recs):.1%}); covered {covered} of "
        f"{prompt} prompt tokens ({covered / max(prompt, 1):.1%})"
    )
    lines.append(
        f"  cow copies: {cows}; admission-path evictions: {evicted}"
    )
    shared = [r.get("shared_blocks", 0) for r in hits]
    if shared:
        ps = percentiles([float(s) for s in shared], qs=(50, 95))
        lines.append(_fmt_row(
            "shared blocks/hit", f"p50 {ps['p50']:.0f}",
            f"p95 {ps['p95']:.0f}",
        ))
    # the fleet rollup, when present, carries the allocator's census
    fleets = [r for r in records if r.get("kind") == "fleet_summary"
              and "prefix_hits" in r]
    if fleets:
        f = fleets[-1]
        lines.append(
            f"  fleet: hit rate {f.get('prefix_hit_rate', 0.0):.1%}, "
            f"evictions {f.get('prefix_evictions', 0)}, "
            f"shared blocks now {f.get('prefix_shared_blocks', 0)}, "
            f"affinity sessions {f.get('affinity_sessions', 0)} "
            f"(evicted {f.get('affinity_evictions', 0)})"
        )
    out["prefix_admissions"] = len(recs)
    out["prefix_hits"] = len(hits)
    out["prefix_hit_rate"] = round(len(hits) / len(recs), 4)
    out["prefix_covered_tokens"] = covered
    out["prefix_covered_frac"] = round(covered / max(prompt, 1), 4)
    out["prefix_cow_copies"] = cows
    out["prefix_evictions"] = evicted
    return lines


def overlap_section(records: List[dict], out: dict) -> List[str]:
    """Host–device overlap (round 15; ``telemetry/overlap.py``):
    per-replica device-busy fraction, the bubble-cause histogram, and
    dispatch-to-completion p50/p95 per program, from ``kind="overlap"``
    records (``scripts/bench_serving.py --wall-clock`` produces them;
    any ledger-armed run does)."""
    from pytorch_distributed_tpu.telemetry.overlap import (
        busy_summary,
        cause_histogram,
        fleet_busy_summary,
        overlap_records,
    )

    launches = overlap_records(records, "launch")
    if not launches:
        return []
    lines = ["== overlap & bubbles =="]
    summary = busy_summary(records)
    lines.append(_fmt_row("replica", "launches", "busy", "window",
                          "busy_frac"))
    for rep, s in sorted(summary.items()):
        lines.append(_fmt_row(
            f"r{rep}", s["launches"], f"{s['busy_s'] * 1e3:.1f}ms",
            f"{s.get('window_s', s['span_s']) * 1e3:.1f}ms",
            f"{s['busy_frac']:.3f}",
        ))
        out[f"overlap_busy_frac_r{rep}"] = s["busy_frac"]
    if len(summary) > 1:
        # shared-device honesty (round 16): per-replica busy windows
        # overlap on a shared device; the interval union is true device
        # utilization and must be reported next to them
        fb = fleet_busy_summary(records)
        lines.append(_fmt_row(
            "union", "-", f"{fb['union_busy_s'] * 1e3:.1f}ms",
            f"{fb['window_s'] * 1e3:.1f}ms",
            f"{fb['union_busy_frac']:.3f}",
        ))
        out["overlap_busy_frac_union"] = fb["union_busy_frac"]
    hist = cause_histogram(records)
    total = sum(h["gap_s"] for h in hist.values())
    if hist:
        lines.append("  bubbles: " + ", ".join(
            f"{cause}={h['gap_s'] * 1e3:.1f}ms({h['count']})"
            for cause, h in sorted(hist.items(),
                                   key=lambda kv: -kv[1]["gap_s"])
        ))
    out["overlap_replicas"] = len(summary)
    out["overlap_launches"] = len(launches)
    out["overlap_bubble_s_total"] = round(total, 6)
    for cause, h in hist.items():
        key = cause.replace("/", "_").replace("-", "_")
        out[f"overlap_bubble_{key}_s"] = round(h["gap_s"], 6)
    # dispatch-to-completion per program: exact for sync/blocking-fenced
    # launches ("done"), the dispatch-return lower bound otherwise
    by_prog: dict = {}
    for r in launches:
        end = r.get("done", r.get("t1", 0.0))
        by_prog.setdefault(r.get("program", "?"), []).append(
            end - r.get("t0", 0.0)
        )
    lines.append(_fmt_row("program", "launches", "d2c p50", "d2c p95"))
    for prog, vals in sorted(by_prog.items(),
                             key=lambda kv: -sum(kv[1]))[:10]:
        ps = percentiles(vals, qs=(50, 95))
        lines.append(_fmt_row(
            prog[:20], len(vals),
            f"{ps['p50'] * 1e3:.3f}ms", f"{ps['p95'] * 1e3:.3f}ms",
        ))
        out[f"overlap_d2c_p95_ms_{prog}"] = round(ps["p95"] * 1e3, 4)
    out["overlap_programs"] = len(by_prog)
    return lines


def span_section(records: List[dict], out: dict) -> List[str]:
    """Request-lifecycle traces (round 14; ``kind="span"`` from
    ``telemetry.reqtrace``): trace count, completeness, open (in-flight
    or abandoned) spans, and lifecycle phase totals —
    ``scripts/explain_request.py`` is the per-rid deep dive."""
    from pytorch_distributed_tpu.telemetry.reqtrace import (
        span_records,
        trace_rids,
        validate_trace,
    )

    spans = span_records(records)
    if not spans:
        return []
    rids = trace_rids(records)
    complete = sum(1 for r in rids if not validate_trace(records, r))
    begins = {(r["trace"], r["span"]) for r in spans
              if r.get("ev") == "begin"}
    ends = {(r["trace"], r["span"]) for r in spans if r.get("ev") == "end"}
    open_spans = len(begins - ends)
    by_phase: dict = {}
    for r in spans:
        if r.get("ev") == "end":
            continue
        if r.get("ev") == "begin":
            by_phase[r.get("name", "?")] = (
                by_phase.get(r.get("name", "?"), 0) + 1
            )
    lines = ["== request traces =="]
    lines.append(
        f"  {len(rids)} traces ({complete} complete, "
        f"{len(rids) - complete} incomplete), {len(spans)} span records, "
        f"{open_spans} open spans"
    )
    top = sorted(by_phase.items(), key=lambda kv: (-kv[1], kv[0]))[:8]
    lines.append("  phases: " + ", ".join(f"{n}={c}" for n, c in top))
    out["span_traces"] = len(rids)
    out["span_complete_traces"] = complete
    out["span_open"] = open_spans
    out["span_records"] = len(spans)
    return lines


def resource_section(records: List[dict], out: dict) -> List[str]:
    """Host resources (round 21; ``kind="resource"`` from
    ``telemetry.hostprof.ResourceMonitor``): RSS and per-tick host-wall
    growth fits against cumulative sessions — the soak's headline — plus
    the newest gc population and tracemalloc top sites when sampled."""
    from pytorch_distributed_tpu.telemetry.scaling import fit_growth

    recs = [r for r in records if r.get("kind") == "resource"]
    if not recs:
        return []
    lines = ["== host resources =="]
    first, last = recs[0], recs[-1]
    lines.append(
        f"  {len(recs)} samples; rss {first.get('rss_mib', 0.0):.1f} → "
        f"{last.get('rss_mib', 0.0):.1f} MiB "
        f"({last.get('rss_source', '?')}); live {last.get('live', 0)}, "
        f"cumulative {last.get('cumulative', 0)} sessions"
    )
    xs = [r.get("cumulative", 0) for r in recs]
    rss_fit = fit_growth(xs, [r.get("rss_mib", 0.0) for r in recs],
                         rel_floor=0.005, abs_floor=1.0)
    walls = [(r.get("cumulative", 0), r["tick_wall_ms_mean"])
             for r in recs if "tick_wall_ms_mean" in r]
    lines.append(
        f"  rss slope {rss_fit['slope'] * 1e4:+.2f} MiB/10k sessions "
        f"({rss_fit['verdict']})"
    )
    out["resource_samples"] = len(recs)
    out["resource_rss_mib_final"] = round(last.get("rss_mib", 0.0), 1)
    out["resource_rss_slope_mib_per_10k"] = round(
        rss_fit["slope"] * 1e4, 3)
    out["resource_rss_verdict"] = rss_fit["verdict"]
    if walls:
        wall_fit = fit_growth([w[0] for w in walls],
                              [w[1] for w in walls], abs_floor=0.05)
        lines.append(
            f"  host wall slope {wall_fit['slope'] * 1e4:+.3f} ms/10k "
            f"sessions ({wall_fit['verdict']}; shared-CPU smoke alarm, "
            f"not a proof — see ANALYSIS.md)"
        )
        out["resource_wall_slope_ms_per_10k"] = round(
            wall_fit["slope"] * 1e4, 4)
        out["resource_wall_verdict"] = wall_fit["verdict"]
    if "gc_objects" in last:
        lines.append(f"  gc objects: {last['gc_objects']}")
        out["resource_gc_objects_final"] = last["gc_objects"]
    sited = [r for r in recs if r.get("tracemalloc_top")]
    if sited:
        lines.append("  tracemalloc top sites (newest sample):")
        for s in sited[-1]["tracemalloc_top"][:5]:
            lines.append(
                f"    {s.get('kib', 0.0):>10.1f} KiB  "
                f"x{s.get('count', 0):<8} {s.get('site', '?')}"
            )
        out["resource_tracemalloc_samples"] = len(sited)
    return lines


def census_section(records: List[dict], out: dict) -> List[str]:
    """Bounded-structure census (round 21; ``kind="census"`` from
    ``telemetry.census.StructCensus``): sweep totals, any bound
    violations or undeclared containers (both are failures), the worst
    bound ratio seen, and the largest structures at their peaks."""
    recs = [r for r in records if r.get("kind") == "census"]
    if not recs:
        return []
    lines = ["== structure census =="]
    violations = sum(r.get("violations", 0) for r in recs)
    undeclared: set = set()
    peaks: dict = {}
    worst_frac, worst_name = 0.0, ""
    for r in recs:
        undeclared.update(r.get("undeclared", []))
        for k, v in (r.get("structures") or {}).items():
            if v > peaks.get(k, -1):
                peaks[k] = v
        if r.get("worst_ratio", 0.0) > worst_frac:
            worst_frac = r["worst_ratio"]
            worst_name = r.get("worst_name", "")
    ok = not violations and not undeclared
    lines.append(
        f"  {len(recs)} sweeps over {len(peaks)} structures: "
        + ("all bounds held"
           if ok else f"{violations} VIOLATIONS, "
                      f"{len(undeclared)} undeclared")
    )
    if worst_name:
        lines.append(
            f"  worst bound ratio {worst_frac:.2f} ({worst_name})"
        )
    if undeclared:
        lines.append("  undeclared: " + ", ".join(sorted(undeclared)))
    for r in recs:
        for v in r.get("violation_details", [])[:5]:
            lines.append(
                f"  VIOLATION {v['name']}: size {v['size']} > bound "
                f"{v['bound']} ({v['kind']})"
            )
    top = sorted(peaks.items(), key=lambda kv: -kv[1])[:8]
    lines.append("  peak sizes: " + ", ".join(
        f"{k}={v}" for k, v in top))
    out["census_sweeps"] = len(recs)
    out["census_violations"] = violations
    out["census_undeclared"] = len(undeclared)
    out["census_ok"] = ok
    out["census_worst_frac"] = round(worst_frac, 4)
    return lines


def ingress_section(records: List[dict], out: dict) -> List[str]:
    """HTTP front door (round 22; ``kind="http"`` from
    ``gateway/server.py``): one record per ``/v1/generate`` connection.
    Status histogram (the SLOGate ladder over the wire: 200 served,
    429 shed, 400 malformed), disconnect→cancel counts, TTFT measured
    at the socket, bytes out, and the worst inter-token stream gap."""
    recs = [r for r in records if r.get("kind") == "http"]
    if not recs:
        return []
    lines = ["== http ingress =="]
    statuses: dict = {}
    for r in recs:
        statuses[r.get("status", 0)] = statuses.get(r.get("status", 0),
                                                    0) + 1
    served = statuses.get(200, 0)
    shed = statuses.get(429, 0)
    disconnects = sum(1 for r in recs if r.get("disconnect"))
    cancelled = sum(1 for r in recs
                    if r.get("disconnect") and r.get("outcome") ==
                    "cancelled")
    lines.append(
        f"  {len(recs)} connections: "
        + ", ".join(f"{s}={n}" for s, n in sorted(statuses.items()))
        + (f"; 429 rate {shed / len(recs):.1%}" if shed else "")
    )
    lines.append(
        f"  disconnects {disconnects} ({cancelled} cancelled "
        f"mid-stream); bytes out "
        f"{sum(r.get('bytes', 0) or 0 for r in recs)}"
    )
    ttfts = [r["ttft_wire"] for r in recs
             if r.get("ttft_wire") is not None]
    if ttfts:
        pct = percentiles(ttfts, qs=(50, 95))
        p50, p95 = pct["p50"], pct["p95"]
        lines.append(
            f"  ttft over the wire p50 {p50 * 1e3:.1f} ms / "
            f"p95 {p95 * 1e3:.1f} ms ({len(ttfts)} streams)"
        )
        out["http_ttft_wire_p50_ms"] = round(p50 * 1e3, 2)
        out["http_ttft_wire_p95_ms"] = round(p95 * 1e3, 2)
    gaps = [r["gap_max_ms"] for r in recs if r.get("gap_max_ms")]
    if gaps:
        lines.append(f"  worst stream gap {max(gaps):.1f} ms")
        out["http_worst_gap_ms"] = round(max(gaps), 2)
    out["http_connections"] = len(recs)
    out["http_served"] = served
    out["http_shed"] = shed
    out["http_rejected"] = statuses.get(400, 0)
    out["http_disconnects"] = disconnects
    out["http_cancelled"] = cancelled
    return lines


def anomaly_section(records: List[dict], out: dict) -> List[str]:
    """Sentinel hits (``kind="anomaly"``): per-series counts and the
    latest excursions with their z-scores and baselines."""
    hits = [r for r in records if r.get("kind") == "anomaly"]
    if not hits:
        return []
    by_series: dict = {}
    for r in hits:
        by_series.setdefault(r.get("series", "?"), []).append(r)
    lines = ["== anomalies =="]
    lines.append("  " + ", ".join(
        f"{s}={len(rs)}" for s, rs in sorted(by_series.items())
    ))
    for r in hits[-5:]:
        src = f" [{r['source']}]" if r.get("source") else ""
        lines.append(
            f"  {r.get('series', '?')}{src}: value "
            f"{r.get('value', float('nan')):.4g} vs median "
            f"{r.get('median', float('nan')):.4g} "
            f"(z={r.get('zscore', float('nan')):.1f})"
        )
    out["anomalies"] = len(hits)
    for s, rs in sorted(by_series.items()):
        out[f"anomalies_{s}"] = len(rs)
    return lines


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("paths", nargs="+", help="telemetry JSONL file(s)")
    p.add_argument("--json", action="store_true",
                   help="append one flat JSON dict (bench.py style)")
    p.add_argument("--require", default=None,
                   help="comma list of sections that MUST be present "
                        "(goodput, serving, warmup, fleet, pressure, "
                        "prefix, overlap, spans, cost, resource, "
                        "census, http, anomaly) — exit non-zero "
                        "otherwise; the ci_check.sh --telemetry-smoke, "
                        "--warmup-smoke, --fleet-smoke, --obs-smoke, "
                        "--pressure-smoke, --trace-smoke, "
                        "--overlap-smoke, --prefix-smoke, --soak-smoke "
                        "and --gateway-smoke gates")
    args = p.parse_args(argv)

    records = load_records(args.paths)
    out: dict = {}
    lines: List[str] = []
    lines += goodput_section(records, out)
    lines += warmup_section(records, out)
    lines += train_section(records, out)
    lines += serving_section(records, out)
    lines += fleet_section(records, out)
    lines += pressure_section(records, out)
    lines += prefix_section(records, out)
    lines += overlap_section(records, out)
    lines += span_section(records, out)
    lines += cost_section(records, out)
    lines += resource_section(records, out)
    lines += census_section(records, out)
    lines += ingress_section(records, out)
    lines += anomaly_section(records, out)
    if not lines:
        print(f"no telemetry records in {args.paths}", file=sys.stderr)
        return 2
    print("\n".join(lines))
    present = {
        "goodput": "goodput_frac" in out,
        "serving": "serving_ttft_p50_ms" in out,
        "warmup": "warmup_programs" in out,
        "fleet": "fleet_replicas" in out,
        "pressure": out.get("pressure_preempts", 0) > 0,
        "prefix": out.get("prefix_admissions", 0) > 0,
        "overlap": out.get("overlap_launches", 0) > 0,
        "spans": out.get("span_traces", 0) > 0,
        "cost": out.get("cost_programs", 0) > 0,
        "resource": out.get("resource_samples", 0) > 0,
        "census": out.get("census_sweeps", 0) > 0,
        "http": out.get("http_connections", 0) > 0,
        "anomaly": out.get("anomalies", 0) > 0,
    }
    if not any(present.values()):
        print("no goodput record, serving latencies, warmup manifest, "
              "fleet records, pressure records, cost cards, or anomalies "
              "found", file=sys.stderr)
        return 2
    required = {s for s in (args.require or "").split(",") if s}
    unknown = required - set(present)
    if unknown:
        print(f"--require: unknown sections {sorted(unknown)}",
              file=sys.stderr)
        return 2
    missing = sorted(s for s in required if not present[s])
    if missing:
        print(f"--require: missing section(s) {missing}", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
