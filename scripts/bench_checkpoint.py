"""Checkpoint save/restore wall-clock at the 135M-param LM size.

Measures the sharded checkpoint path (utils.checkpoint.save_sharded /
load_sharded) on a full AdamW TrainState: params + 2 moments, fp32 —
~1.6 GB. Runs on the CPU backend on purpose: through this environment's
tunneled TPU runtime the device→host link is ~24 MB/s (PERF_NOTES.md §1),
so an on-chip run times the tunnel, not the checkpoint code; on a real
TPU VM the device→host hop rides PCIe at GB/s and the serialize+disk cost
measured here dominates. Emits one JSON line:

  {"ckpt_params_m": ..., "ckpt_bytes_mb": ..., "ckpt_save_s": ...,
   "ckpt_restore_s": ..., "ckpt_mb_per_s": ...}

Restore rows are LABELED cold vs warm (ISSUE 8, reconciling ADVICE §4's
r4 0.59 s vs r5 11.99 s): ``ckpt_restore_warm_s`` is the median of N
page-cache-warm restores (the bytes were just written — a memcpy, not a
disk read), ``ckpt_restore_cold_s`` restores after evicting the
checkpoint's pages (``posix_fadvise DONTNEED``, no root needed) so it
pays the real disk read, and both are co-quoted with same-minute disk
probes (``ckpt_disk_mb_s`` write, ``ckpt_disk_read_mb_s`` cold read)
plus the read-bound floor ``ckpt_restore_disk_bound_s`` — so a restore
number is interpretable as efficiency-vs-disk instead of a
page-cache-state lottery. ``ckpt_restore_s`` keeps its historical
meaning (first restore right after save ≈ warm) for series continuity;
see PERF_NOTES §10.

``--reshard`` appends the elastic-restore section (reshard/, ROADMAP
item 4): the same dp4xtp2+FSDP checkpoint restored onto its own mesh
(exact-block fast path) vs onto (2,1,2) and (8,1,1) (cross-topology
block assembly), plus the offline repartition cost and the exact-path
restore it buys — keys ``ckpt_reshard_*``. Runs on 8 virtual CPU
devices (forced before jax import), so pass it on a dedicated
invocation if you want the headline sections on default devices.

Usage: python scripts/bench_checkpoint.py [--small] [--reshard]
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "--reshard" in sys.argv:
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8"
        ).strip()
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np


def _evict_page_cache(path: str) -> bool:
    """Best-effort eviction of ``path``'s files from the page cache:
    fsync any dirty pages, then ``posix_fadvise(DONTNEED)`` — works on
    our own files without root (DONTNEED drops only clean pages, hence
    the fsync first). Returns False when the platform has no fadvise, so
    the cold row can be labeled honestly instead of silently warm."""
    if not hasattr(os, "posix_fadvise"):
        return False
    paths = []
    if os.path.isdir(path):
        for root, _dirs, files in os.walk(path):
            paths += [os.path.join(root, f) for f in files]
    else:
        paths = [path]
    for p in paths:
        try:
            fd = os.open(p, os.O_RDONLY)
            try:
                os.fsync(fd)
                os.posix_fadvise(fd, 0, 0, os.POSIX_FADV_DONTNEED)
            finally:
                os.close(fd)
        except OSError:
            return False
    return True


def main() -> None:
    from pytorch_distributed_tpu.models.transformer import TransformerConfig
    from pytorch_distributed_tpu.ops.optim import build_optimizer
    from pytorch_distributed_tpu.train.lm import create_lm_state
    from pytorch_distributed_tpu.utils.checkpoint import (
        load_sharded,
        save_sharded,
    )

    small = "--small" in sys.argv
    cfg = TransformerConfig(
        vocab_size=32000 if not small else 1024,
        num_layers=12 if not small else 2,
        num_heads=12 if not small else 2,
        embed_dim=768 if not small else 64,
        max_seq_len=1024 if not small else 64,
        dtype=jnp.float32,
    )
    tx = build_optimizer("adamw", 1e-4)
    state = create_lm_state(cfg, tx, jax.random.key(0), init_len=64)
    n_params = sum(x.size for x in jax.tree.leaves(state.params))
    payload = {"state": state, "epoch": 1, "step": 100, "best_ppl": 12.5}
    total_bytes = sum(
        x.size * x.dtype.itemsize
        for x in jax.tree.leaves(payload)
        if hasattr(x, "dtype")
    )

    from pytorch_distributed_tpu.utils.checkpoint import Checkpointer

    d = tempfile.mkdtemp(prefix="ckpt_bench_")
    try:
        # Concurrent raw-disk ceiling: the sync save is DISK-BOUND (the
        # round-5 analysis — raw write+fsync of the same byte count
        # measured 13.3 s = 116 MB/s on the shared disk the day the
        # "regression" was chased; r3's 9.7 s was a faster-disk day).
        # Measure it HERE, same minute, so ckpt_save_s is interpretable
        # as efficiency-vs-disk instead of a disk-weather lottery.
        probe_mb = 512 if not small else 8
        probe = np.ones(probe_mb * 2**20, np.uint8)
        pp = os.path.join(d, "disk_probe.bin")
        t0 = time.perf_counter()
        with open(pp, "wb") as f:
            f.write(memoryview(probe))
            f.flush()
            os.fsync(f.fileno())
        disk_mb_s = probe_mb / (time.perf_counter() - t0)
        os.remove(pp)
        del probe

        t0 = time.perf_counter()
        save_sharded(os.path.join(d, "latest.ckpt"), payload)
        save_s = time.perf_counter() - t0

        ckpt_path = os.path.join(d, "latest.ckpt")

        def timed_restore():
            t0 = time.perf_counter()
            back = load_sharded(ckpt_path, payload)
            # touch a leaf so lazy work can't hide
            float(np.asarray(
                jax.tree.leaves(back["state"].params)[0]
            ).ravel()[0])
            return time.perf_counter() - t0

        # historical row (r1-r5 series continuity): the first restore
        # right after save — page-cache WARM unless the box evicted the
        # bytes between save and restore, which is exactly the r4 0.59 s
        # vs r5 11.99 s ambiguity the labeled rows below resolve
        restore_s = timed_restore()

        # labeled WARM: median-of-3 cache-hot restores (a memcpy rate)
        warm_restores = [timed_restore() for _ in range(3)]

        # same-minute cold disk READ probe: evict the probe's own pages,
        # read it back — the r/w twin of the write probe above
        probe2 = np.ones(probe_mb * 2**20, np.uint8)
        pp = os.path.join(d, "disk_probe_read.bin")
        with open(pp, "wb") as f:
            f.write(memoryview(probe2))
            f.flush()
            os.fsync(f.fileno())
        del probe2
        disk_read_mb_s = None
        if _evict_page_cache(pp):
            t0 = time.perf_counter()
            with open(pp, "rb") as f:
                while f.read(32 * 2**20):
                    pass
            disk_read_mb_s = probe_mb / (time.perf_counter() - t0)
        os.remove(pp)

        # labeled COLD: evict the checkpoint's pages, restore once —
        # the relaunch-after-preemption number, disk-read bound
        cold_restore_s = (
            timed_restore() if _evict_page_cache(ckpt_path) else None
        )

        # the non-stalling trainer path: the step loop pays ONLY the
        # device→host snapshot; write rides a thread, commit lands at the
        # next epoch-boundary wait()
        ck = Checkpointer(d)
        # trainers call warm_for at init so the arena fault-in (the
        # dominant first-save cost on this kernel) overlaps the first XLA
        # compile; measure it as the background cost it is
        t0 = time.perf_counter()
        ck.warm_for(payload)
        ck._warm_thread.join()
        warm_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        ck.save_best_sharded(payload, block=False)
        stall_first_s = time.perf_counter() - t0  # arena pre-faulted
        # Steady state over FIVE saves, quoted as median + spread: the
        # r4 driver captured a single second-save sample of 1.84 s that
        # no instrumented rerun could reproduce (17 in-situ saves all
        # 0.32-0.69 s; /proc counters showed no reclaim/THP/steal — a
        # transient of the shared 1-core box). A single sample measures
        # the box's weather; the median measures the checkpointer.
        stalls = []
        for _ in range(5):
            ck.wait()  # commit previous (joins its write thread)
            t0 = time.perf_counter()
            ck.save_best_sharded(payload, block=False)
            stalls.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        ck.wait()
        commit_s = time.perf_counter() - t0

        reshard_keys = {}
        if "--reshard" in sys.argv:
            reshard_keys = _bench_reshard(d, cfg, tx, small)
    finally:
        shutil.rmtree(d, ignore_errors=True)

    print(json.dumps({
        "ckpt_params_m": round(n_params / 1e6, 1),
        "ckpt_bytes_mb": round(total_bytes / 2**20, 1),
        "ckpt_disk_mb_s": round(disk_mb_s, 1),
        "ckpt_save_s": round(save_s, 2),
        "ckpt_save_disk_bound_s": round(total_bytes / 2**20 / disk_mb_s, 2),
        "ckpt_restore_s": round(restore_s, 2),
        "ckpt_restore_warm_s": round(float(np.median(warm_restores)), 2),
        "ckpt_restore_warm_min_s": round(min(warm_restores), 2),
        "ckpt_restore_warm_max_s": round(max(warm_restores), 2),
        **({"ckpt_restore_cold_s": round(cold_restore_s, 2)}
           if cold_restore_s is not None else {}),
        **({"ckpt_disk_read_mb_s": round(disk_read_mb_s, 1),
            "ckpt_restore_disk_bound_s": round(
                total_bytes / 2**20 / disk_read_mb_s, 2)}
           if disk_read_mb_s else {}),
        "ckpt_arena_warm_bg_s": round(warm_s, 2),
        "ckpt_stall_first_s": round(stall_first_s, 2),
        "ckpt_stall_s": round(float(np.median(stalls)), 2),
        "ckpt_stall_min_s": round(min(stalls), 2),
        "ckpt_stall_max_s": round(max(stalls), 2),
        "ckpt_commit_after_overlap_s": round(commit_s, 2),
        "ckpt_mb_per_s": round(total_bytes / 2**20 / max(save_s, 1e-9), 1),
        **reshard_keys,
    }))


def _bench_reshard(d: str, cfg, tx, small: bool) -> dict:
    """Elastic-restore timings: one dp4xtp2+FSDP checkpoint restored
    onto three topologies, plus the offline repartition path."""
    import dataclasses

    from pytorch_distributed_tpu import reshard
    from pytorch_distributed_tpu.parallel import make_mesh
    from pytorch_distributed_tpu.train.lm import (
        create_lm_state,
        shard_lm_state,
    )
    from pytorch_distributed_tpu.utils.checkpoint import save_sharded

    tp_cfg = dataclasses.replace(cfg, model_axis="model", tp_size=2)
    state = create_lm_state(tp_cfg, tx, jax.random.key(1), init_len=64)
    devs = jax.devices()

    def mesh_of(dp, sp, mp):
        return make_mesh(devs[: dp * sp * mp], data_parallel=dp,
                         seq_parallel=sp, model_parallel=mp)

    mesh_a = mesh_of(4, 1, 2)
    placed, _ = shard_lm_state(mesh_a, state, tp_cfg, fsdp=True)
    src = os.path.join(d, "reshard_src.ckpt")
    save_sharded(src, {"state": placed, "epoch": 1, "step": 7,
                       "best_ppl": 5.0})

    def timed_restore(path, dp, sp, mp, target_cfg, fsdp):
        mesh = mesh_of(dp, sp, mp)
        specs = reshard.resolve_lm_state_specs(state, mesh, target_cfg,
                                               fsdp=fsdp)
        template = {"state": state, "epoch": 0, "step": 0, "best_ppl": 0.0}
        shardings = reshard.payload_shardings(mesh, template, specs)
        t0 = time.perf_counter()
        back, info = reshard.load_elastic(path, template, shardings,
                                          mesh=mesh)
        jax.block_until_ready(jax.tree.leaves(back["state"].params))
        return time.perf_counter() - t0, info

    cfg1 = dataclasses.replace(cfg, model_axis=None, tp_size=1)
    same_s, same_info = timed_restore(src, 4, 1, 2, tp_cfg, True)
    to22_s, to22_info = timed_restore(src, 2, 1, 2, tp_cfg, True)
    to81_s, _ = timed_restore(src, 8, 1, 1, cfg1, True)

    dst = os.path.join(d, "reshard_22.ckpt")
    t0 = time.perf_counter()
    reshard.repartition(src, dst, {"data": 2, "seq": 1, "model": 2},
                        config=tp_cfg, fsdp=True)
    offline_s = time.perf_counter() - t0
    pre_s, pre_info = timed_restore(dst, 2, 1, 2, tp_cfg, True)

    return {
        # same-mesh restore: every region exact-block (the r5 baseline)
        "ckpt_reshard_same_mesh_s": round(same_s, 2),
        "ckpt_reshard_same_assembled": same_info.assembled_regions,
        # cross-topology elastic restores: block assembly on the fly
        "ckpt_reshard_to_2x2_s": round(to22_s, 2),
        "ckpt_reshard_to_2x2_assembled": to22_info.assembled_regions,
        "ckpt_reshard_to_8x1_s": round(to81_s, 2),
        # offline repartition + the exact-path restore it buys
        "ckpt_reshard_offline_s": round(offline_s, 2),
        "ckpt_reshard_prepartitioned_s": round(pre_s, 2),
        "ckpt_reshard_prepartitioned_assembled":
            pre_info.assembled_regions,
    }


if __name__ == "__main__":
    main()
