"""Reproduce the reference's result.png-shaped comparison table.

The reference publishes five rows — single GPU fp32, nn.DataParallel,
multi-process DDP, AMP+DDP, AMP×4 nodes — with epoch time, GPU util and
memory (``/root/reference/result.png``, ``README.md:27-40``). This script
produces the TPU-native analog and writes BENCH_TABLE.md:

- real-chip rows (run with the TPU visible): single-chip fp32 and bf16
  ResNet-50, measured with the same pipelined-dispatch method as bench.py;
- scaling-shape rows (run on 8 virtual CPU devices): the SAME compiled SPMD
  train step over a 1-device vs 8-device mesh, tiny ResNet — demonstrating
  the DP/DDP/AMP code paths and their scaling efficiency where no 8-chip
  hardware is reachable. CPU img/s is not comparable to TPU img/s and is
  reported only as a 8-dev/1-dev ratio.

Single/DP/DDP collapse into one program here (SURVEY.md §7): the mesh is
the difference, so the "DP row" exercises exactly what an 8-chip pod runs.

Usage:
    python scripts/bench_table.py            # orchestrates all rows
    python scripts/bench_table.py --row X    # child mode, one JSON line
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASELINE_ROWS = [
    # (config, epoch_s, util_pct, mem_gb) transcribed from result.png
    ("single GPU fp32 (bs400)", 1786.78, 99.5, 39.92),
    ("nn.DataParallel 8 GPU", 984.58, 59.8, 39.92),
    ("DDP 8 GPU", 239.40, 99.5, 39.92),
    ("AMP+DDP 8 GPU", 230.98, 88.8, 24.48),
    ("AMP+DDP 32 GPU", 54.50, 79.2, 24.48),
]
IMAGENET_TRAIN = 1_281_167


def run_row(row: str) -> dict:
    sys.path.insert(0, REPO)
    import jax

    if row.startswith("cpu_"):
        # The site TPU plugin overrides JAX_PLATFORMS from the environment;
        # forcing the config is the only reliable way onto the CPU backend.
        jax.config.update("jax_platforms", "cpu")
    assert jax.devices(), "no devices"
    if row.startswith("cpu_") and len(jax.devices()) < 8:
        raise RuntimeError(
            f"expected 8 virtual CPU devices, got {jax.devices()}"
        )
    import jax.numpy as jnp

    import bench
    from pytorch_distributed_tpu.parallel import make_mesh, single_device_mesh

    tiny = row.startswith("cpu_")
    dtype = jnp.bfloat16 if ("bf16" in row or "amp" in row) else jnp.float32
    per_dev_bs = 16 if tiny else int(os.environ.get("BENCH_BS", "128"))
    mesh = make_mesh() if "8dev" in row else single_device_mesh()
    n_dev = int(mesh.devices.size)
    bs = per_dev_bs * n_dev
    # Same build/timing/round-trip-correction path as the headline bench,
    # including its fused-bottleneck default (BENCH_FUSED).
    fused = os.environ.get("BENCH_FUSED", "1") == "1" and not tiny
    img_s, step_s, _ = bench.run(
        bs, tiny, dtype=dtype, mesh=mesh, measure_duty=False,
        warmup=5, iters=10 if tiny else 30, fused=fused,
    )
    return {"row": row, "n_dev": n_dev, "batch_size": bs,
            "img_s": round(img_s, 2), "step_ms": round(step_s * 1e3, 2),
            "platform": jax.devices()[0].platform}


def child(row: str, cpu: bool) -> dict:
    env = dict(os.environ)
    if cpu:
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    out = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--row", row],
        env=env, capture_output=True, text=True, timeout=1200, cwd=REPO)
    for line in reversed(out.stdout.strip().splitlines()):
        try:
            return json.loads(line)
        except json.JSONDecodeError:
            continue
    raise RuntimeError(f"row {row} failed:\n{out.stdout}\n{out.stderr}")


def main() -> None:
    if "--row" in sys.argv:
        row = sys.argv[sys.argv.index("--row") + 1]
        print(json.dumps(run_row(row)))
        return

    results = {}
    for row in ("tpu_single_fp32", "tpu_single_bf16"):
        try:
            results[row] = child(row, cpu=False)
            print(f"{row}: {results[row]['img_s']} img/s", file=sys.stderr)
        except Exception as e:
            print(f"{row} skipped: {e}", file=sys.stderr)
    for row in ("cpu_single_fp32", "cpu_8dev_fp32", "cpu_8dev_bf16_amp"):
        results[row] = child(row, cpu=True)
        print(f"{row}: {results[row]['img_s']} img/s", file=sys.stderr)

    lines = [
        "# BENCH_TABLE — reference result.png comparison (round 2)",
        "",
        "## Reference (8×A100 cluster, ImageNet epoch)",
        "",
        "| config | epoch (s) | util % | mem (GB) | derived img/s |",
        "|---|---|---|---|---|",
    ]
    for cfg, es, util, mem in BASELINE_ROWS:
        lines.append(f"| {cfg} | {es:.0f} | {util} | {mem} | {IMAGENET_TRAIN/es:.0f} |")
    lines += [
        "",
        "## This framework — real TPU v5e chip (measured)",
        "",
        "| config | devices | img/s | projected ImageNet epoch (s) | vs ref single-GPU |",
        "|---|---|---|---|---|",
    ]
    ref_single = IMAGENET_TRAIN / BASELINE_ROWS[0][1]
    for row, label in (("tpu_single_fp32", "single chip fp32"),
                       ("tpu_single_bf16", "single chip bf16 (AMP row analog)")):
        r = results.get(row)
        if r:
            lines.append(
                f"| {label} | {r['n_dev']} | {r['img_s']:.0f} | "
                f"{IMAGENET_TRAIN / r['img_s']:.0f} | {r['img_s']/ref_single:.2f}× |")
    lines += [
        "",
        "## Code-path rows — 8 virtual CPU devices (same SPMD program a pod runs)",
        "",
        "All 8 virtual devices share ONE physical CPU core, so the ratio is",
        "bounded by the core, not by the parallelism — these rows prove the",
        "DP/DDP/AMP train-step code paths compile and execute over an 8-way",
        "mesh (global batch ×8), not hardware scaling. True multi-chip",
        "scaling needs a pod; the dryrun_multichip entry point and",
        "tests/test_multihost.py validate the program + rendezvous sides.",
        "",
        "| config | devices | global batch | img/s (1-core bound) |",
        "|---|---|---|---|",
    ]
    for row, label in (("cpu_single_fp32", "single device (tiny)"),
                       ("cpu_8dev_fp32", "DP/DDP mesh ×8 (tiny)"),
                       ("cpu_8dev_bf16_amp", "AMP + DP mesh ×8 (tiny)")):
        r = results[row]
        lines.append(f"| {label} | {r['n_dev']} | {r['batch_size']} | "
                     f"{r['img_s']:.0f} |")
    lines += [
        "",
        "Method: pipelined async dispatch, one scalar sync (see PERF_NOTES.md);",
        "projected epoch = 1,281,167 images / measured img/s, the same derivation",
        "BASELINE.md applies to result.png. Multi-process DDP is the identical",
        "program over a multi-host mesh (tests/test_multihost.py exercises the",
        "2-process rendezvous path).",
        "",
    ]
    path = os.path.join(REPO, "BENCH_TABLE.md")
    with open(path, "w") as f:
        f.write("\n".join(lines))
    print(f"wrote {path}", file=sys.stderr)


if __name__ == "__main__":
    main()
