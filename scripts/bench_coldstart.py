"""Cold-vs-warm start benchmark: the compilecache/ subsystem's proof.

Runs the same workload in TWO child processes sharing one persistent
compile cache directory:

- **cold**: fresh (empty) cache — warmup compiles every registry program
  from scratch; the goodput ledger's ``compile`` fraction is the cold-
  start tax;
- **warm**: second process start against the now-populated cache — every
  program loads from disk, the warmup manifest reports cache hits, and
  the compile fraction must collapse (``--min-ratio``, default 5x, is
  asserted: exit non-zero otherwise — this is the acceptance gate
  ``scripts/ci_check.sh --warmup-smoke`` runs).

``--include-lazy`` adds a third child with NO warmup and NO cache: the
pre-compilecache behavior, where the first request into every prefill
bucket eats its compile mid-traffic — its ``cold_requests`` count and
all-vs-warm-only TTFT gap demonstrate the honesty fix (per-request
``cold`` flag) this subsystem's satellite added.

    python scripts/bench_coldstart.py                     # serve, tiny
    python scripts/bench_coldstart.py --mode train
    python scripts/bench_coldstart.py --include-lazy --json out.json
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def _parse() -> argparse.Namespace:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--mode", default="serve", choices=["serve", "train"],
                   help="workload: paged-serving cycle or LM trainer fit")
    p.add_argument("--compile-cache-dir", default=None,
                   help="cache dir shared by the children (default: a "
                        "fresh temp dir, removed afterwards)")
    p.add_argument("--requests", type=int, default=48,
                   help="serve mode: synthetic requests")
    p.add_argument("--max-new", type=int, default=32,
                   help="serve mode: decode budget per request")
    p.add_argument("--slots", type=int, default=4, help="decode lanes")
    p.add_argument("--steps", type=int, default=300,
                   help="train mode: approximate train steps")
    p.add_argument("--min-ratio", type=float, default=5.0,
                   help="assert cold/warm compile-fraction ratio >= this "
                        "(0 disables the assertion)")
    p.add_argument("--include-lazy", action="store_true",
                   help="also run a no-warmup/no-cache child (the lazy "
                        "mid-traffic-compile baseline)")
    p.add_argument("--json", default=None,
                   help="write the flat bench dict to this path too")
    p.add_argument("--child", default=None, help=argparse.SUPPRESS)
    p.add_argument("--metrics-out", default=None, help=argparse.SUPPRESS)
    return p.parse_args()


# ---------------------------------------------------------------------------
# child workloads (run in subprocesses so each start is a real cold/warm
# process boundary — in-process jit caches cannot leak between runs)
# ---------------------------------------------------------------------------


def _child_serve(args, t_start: float) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from pytorch_distributed_tpu.models.transformer import (
        TransformerLM,
        tiny_config,
    )
    from pytorch_distributed_tpu.serving import Scheduler
    from pytorch_distributed_tpu.utils.profiling import MetricsLogger

    cfg = tiny_config(attention="dense", max_seq_len=128)
    params = TransformerLM(cfg).init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    rng = np.random.default_rng(0)
    prompts = [
        rng.integers(1, cfg.vocab_size,
                     size=int(l)).astype(np.int32)
        for l in rng.integers(4, cfg.max_seq_len - args.max_new,
                              size=args.requests)
    ]
    with MetricsLogger(args.metrics_out) as mlog:
        s = Scheduler(cfg, params, n_slots=args.slots, block_len=16,
                      prefill_chunk=32, metrics_log=mlog)
        if args.child in ("cold", "warm"):
            s.warmup(background=False)
        for prompt in prompts:
            s.submit(prompt, args.max_new)
        first_token_from_start = None
        while s.queue or s.resident:
            if s.step() and first_token_from_start is None:
                first_token_from_start = time.perf_counter() - t_start
        m = s.metrics()
        mlog.log(kind="goodput", **s.goodput.report())
        mlog.log(kind="serving_summary", layout="paged", **m)
    gp = s.goodput.report()
    return {
        "compile_s": gp["compile_s"],
        "trace_s": gp["trace_s"],
        "compile_frac": gp["compile_frac"],
        "wall_s": gp["wall_s"],
        "cold_requests": m["cold_requests"],
        "ttft_p50_s": m.get("ttft_p50_s"),
        "ttft_warm_p50_s": m.get("ttft_warm_p50_s"),
        "first_token_from_start_s": first_token_from_start,
    }


def _child_train(args, t_start: float) -> dict:
    import jax

    from pytorch_distributed_tpu.data import SyntheticTokens
    from pytorch_distributed_tpu.models.transformer import tiny_config
    from pytorch_distributed_tpu.parallel import make_mesh
    from pytorch_distributed_tpu.train import LMTrainer, LMTrainerConfig

    mesh = make_mesh(jax.devices()[:4], data_parallel=2, seq_parallel=2)
    cfg = tiny_config(attention="ring")
    out_dir = os.path.join(os.path.dirname(args.metrics_out),
                           f"trainer_{args.child}")
    tc = LMTrainerConfig(
        epochs=1, batch_size=2, save_dir=out_dir, log_every=8,
        warmup=args.child in ("cold", "warm"),
        compile_cache_dir=(args.compile_cache_dir
                           if args.child in ("cold", "warm") else None),
        metrics_out=args.metrics_out,
    )
    # batch_size 2 x 2 data replicas = 4 seqs/step
    train = SyntheticTokens(args.steps * 4, 32, 128)
    trainer = LMTrainer(cfg, train, SyntheticTokens(8, 32, 128, seed=1),
                        tc, mesh=mesh)
    trainer.fit()
    trainer.assert_registry_covers()
    gp = trainer.goodput.report()
    return {
        "compile_s": gp["compile_s"],
        "trace_s": gp["trace_s"],
        "compile_frac": gp["compile_frac"],
        "wall_s": gp["wall_s"],
        "fit_from_start_s": time.perf_counter() - t_start,
    }


def _run_child(mode: str, child: str, cache_dir: str, work: str,
               args) -> dict:
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    if mode == "train":
        flags = env.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in flags:
            env["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
    cmd = [
        sys.executable, os.path.abspath(__file__),
        "--mode", mode, "--child", child,
        "--compile-cache-dir", cache_dir,
        "--metrics-out", os.path.join(work, f"{child}.jsonl"),
        "--requests", str(args.requests), "--max-new", str(args.max_new),
        "--slots", str(args.slots), "--steps", str(args.steps),
    ]
    out = subprocess.run(cmd, env=env, capture_output=True, text=True,
                         timeout=1800)
    if out.returncode != 0:
        raise SystemExit(
            f"{child} child failed (rc={out.returncode}):\n{out.stderr}"
        )
    return json.loads(out.stdout.strip().splitlines()[-1])


def main() -> int:
    args = _parse()

    if args.child is not None:
        t_start = time.perf_counter()
        from pytorch_distributed_tpu.utils.env import set_env

        set_env("202607")
        if args.child in ("cold", "warm"):
            from pytorch_distributed_tpu.compilecache import (
                enable_persistent_cache,
            )

            enable_persistent_cache(args.compile_cache_dir)
        result = (_child_serve if args.mode == "serve"
                  else _child_train)(args, t_start)
        print(json.dumps(result))
        return 0

    own_tmp = args.compile_cache_dir is None
    cache_dir = args.compile_cache_dir or tempfile.mkdtemp(
        prefix="pdt_coldstart_"
    )
    work = tempfile.mkdtemp(prefix="pdt_coldstart_work_")
    try:
        results = {}
        if args.include_lazy:
            results["lazy"] = _run_child(args.mode, "lazy", cache_dir,
                                         work, args)
        results["cold"] = _run_child(args.mode, "cold", cache_dir, work,
                                     args)
        results["warm"] = _run_child(args.mode, "warm", cache_dir, work,
                                     args)

        # warm-start gate: the warmup manifest of the WARM child must
        # report persistent-cache hits (else the cache never persisted)
        warm_records = [
            json.loads(line)
            for line in open(os.path.join(work, "warm.jsonl"))
        ]
        warm_hits = sum(1 for r in warm_records
                        if r.get("kind") == "warmup" and r.get("cache_hit"))

        cold_frac = results["cold"]["compile_frac"]
        warm_frac = results["warm"]["compile_frac"]
        ratio = cold_frac / max(warm_frac, 1e-9)
        out = {"bench": f"coldstart_{args.mode}",
               "compile_frac_ratio": round(ratio, 2),
               "warm_warmup_cache_hits": warm_hits}
        for tag, r in results.items():
            for k, v in r.items():
                out[f"{tag}_{k}"] = (round(v, 4)
                                     if isinstance(v, float) else v)
        print(json.dumps(out, indent=2))
        if args.json:
            with open(args.json, "w") as f:
                json.dump(out, f, indent=2)

        failures = []
        if warm_hits < 1:
            failures.append("warm run's warmup manifest reports zero "
                            "persistent-cache hits")
        if args.min_ratio > 0 and ratio < args.min_ratio:
            failures.append(
                f"compile fraction only improved {ratio:.1f}x "
                f"(cold {cold_frac:.3f} -> warm {warm_frac:.3f}); "
                f"required {args.min_ratio:.1f}x"
            )
        if args.mode == "serve" and results["warm"]["cold_requests"]:
            failures.append(
                f"warm serve run still had "
                f"{results['warm']['cold_requests']} cold requests"
            )
        if failures:
            print("FAIL: " + "; ".join(failures), file=sys.stderr)
            return 1
        print(f"OK: compile fraction {cold_frac:.3f} -> {warm_frac:.3f} "
              f"({ratio:.1f}x), {warm_hits} warm cache hits")
        return 0
    finally:
        shutil.rmtree(work, ignore_errors=True)
        if own_tmp:
            shutil.rmtree(cache_dir, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
