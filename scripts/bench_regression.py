"""Round-over-round bench regression gate with per-key noise bands.

The BENCH_r0N.json trajectory is the repo's performance history, but
nothing ever COMPARED two rounds — an 11.99 s vs 0.59 s swing (ADVICE r5
§4) sat in the record for a round before a human noticed. This script
diffs the newest round against the previous one, key by key, with noise
bands wide enough that the documented measurement weather (tunnel timing
±6%, shared-disk bandwidth 2×; PERF_NOTES §5/§8) does not page anyone,
and exits non-zero when a key regresses OUTSIDE its band — the optional
``ci_check.sh --bench-regression`` gate.

Direction is inferred from the key: throughput-like keys (``*_img_s``,
``*_tok_s``, ``*_tflops``, ``*_gb_s``, ``*_mb_s``, ``*_per_s``,
``*_frac`` where higher is better is NOT assumed — fractions are
skipped) regress when they DROP below ``previous × (1 - band)``;
latency/time keys (``*_ms``, ``*_s``) regress when they RISE above
``previous × (1 + band)``. Keys that are not numbers, appear in only one
round, or match the skip list are reported as informational.

Bands: 10% default; disk/checkpoint keys get 150% (the measured 2×
disk-weather swing, PERF_NOTES §8) — a regression there must be
structural, not meteorological. Override any band with
``--band key=frac`` (repeatable).

``--blocksan-off`` is a separate structural gate (round 18): with
``PDT_BLOCKSAN`` unset, the block-lifecycle sanitizer must be fully
detached — ``maybe_sanitizer()`` returns None and a fresh
``BlockAllocator`` carries ``sanitizer=None``, so every hook site in the
hot alloc/free path costs one attribute load + is-None branch and the
bench numbers above measure the same code the seed measured. It also
micro-times alloc/free cycles detached vs attached (informational, with
a generous flake-proof bound) and exits non-zero if the detached path is
somehow slower than the attached one.

Usage:
    python scripts/bench_regression.py CURRENT.json PREVIOUS.json [--json]
    python scripts/bench_regression.py --auto [--dir .]   # two newest rounds
    python scripts/bench_regression.py --blocksan-off [--json]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Dict, Optional, Tuple

REPO_DEFAULT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: default relative noise band
DEFAULT_BAND = 0.10
#: key-pattern bands for known-noisy measurements (first match wins)
BAND_OVERRIDES: Tuple[Tuple[str, float], ...] = (
    # direction-aware fractions (round 15): bounded in [0, 1], so the
    # wall-clock catch-all's 150% band below would make them
    # unflaggable — a halved device-busy fraction IS the regression the
    # async-refactor A/B exists to catch. Ordered first: first match
    # wins.
    (r"device_busy_frac", 0.5),
    (r"gap_accounted_frac", 0.10),
    # prefix-cache keys (round 17): token accounting is deterministic
    # per trace but the ratio moves with trace mix; hit rate is bounded
    # in [0, 1] like the busy fractions above
    (r"serving_prefix_hit_rate", 0.25),
    (r"^serving_prefix_", 0.5),
    # the wall-clock fleet bench (round 15) measures MACHINE wall on a
    # shared box — the same weather class as the disk keys; its CPU
    # magnitudes are additionally backend-marked as not-a-claim
    # (PERF_NOTES §11)
    (r"^serving_wallclock_", 1.5),
    # round-21 soak keys: the growth SLOPES are the claim (down is
    # good; direction overrides below), but their magnitudes ride the
    # same shared-box weather as the wall-clock bench — a slope near
    # zero makes relative bands twitchy, so the band is wide and the
    # census/verdict gates (strings + ci_check --soak-smoke) carry the
    # hard pass/fail instead
    (r"^serving_soak_", 1.5),
    # round-20 kernel-variant columns (fp8 / split-S / tuned): decode
    # tok/s over a tiny model is scheduler-noise-dominated even on TPU;
    # the ratios move with it. On a CPU backend these rows are skipped
    # entirely (interpreter timing — see the honesty skip in compare()).
    (r"^serving_kernel_.*_over_", 0.5),
    (r"^serving_kernel_", 0.35),
    # shared-disk weather moves raw bandwidth 2x day to day (PERF_NOTES
    # §8); anything disk-bound inherits that swing
    (r"^ckpt_", 1.5),
    (r"disk", 1.5),
    # single-sample latency spreads on a contended 1-core box
    (r"stall", 1.5),
    (r"wall_s$", 0.5),
)

#: keys that are configuration, not measurement — plus the same-run
#: link probes (ADVICE §6): they exist to EXPLAIN cross-day swings
#: (environment weather co-quoted with every serving row), so gating
#: them would page on the weather itself
SKIP_PATTERNS = (
    r"batch_size$", r"^platform$", r"^device$", r"^unit$", r"^metric$",
    r"_mode$", r"^host_cores$", r"params_m$", r"bytes_mb$", r"_len$",
    r"slots$", r"_lens$", r"tokens$", r"_frac$", r"vs_baseline",
    r"^probe_",
    # tuned-config provenance: the CONFIG the autotuner picked, not a
    # measurement (a different winner is news, not a regression)
    r"tuned_split_s$", r"tuned_block_len$", r"tuned_loaded$",
)

_HIGHER_BETTER = re.compile(
    r"(_img_s|_tok_s|tok_s$|_tflops|_gb_s|_mb_s|_per_s|throughput|"
    r"goodput|_speedup|duty_cycle|_ratio.*over|img_s$)"
)
_LOWER_BETTER = re.compile(r"(_ms$|_s$|_ms_|latency|overhead)")


def band_for(key: str, overrides: Dict[str, float]) -> float:
    if key in overrides:
        return overrides[key]
    for pattern, band in BAND_OVERRIDES:
        if re.search(pattern, key):
            return band
    return DEFAULT_BAND


#: direction overrides checked BEFORE the skip list: fractions are
#: normally configuration-like and skipped, but device-busy fraction is
#: a direction-aware measurement (higher = less idle device) — the
#: round-15 overlap keys the async-refactor A/B will move
DIRECTION_OVERRIDES: Tuple[Tuple[str, str], ...] = (
    (r"device_busy_frac", "up"),
    (r"gap_accounted_frac", "up"),
    # prefix-cache keys (round 17): hit rate and the off/on token ratio
    # regress DOWN (less sharing); admitted tokens and fresh blocks per
    # request regress UP (sharing doing less work per request is the
    # whole point)
    (r"serving_prefix_hit_rate", "up"),
    (r"serving_prefix_admit_tok_ratio", "up"),
    (r"serving_prefix_admit_tok_per_req", "down"),
    (r"serving_prefix_fresh_blocks_per_req", "down"),
    # round-20 kernel columns: variant-over-baseline throughput ratios
    # regress DOWN when the variant loses ground; plain tok/s and p95
    # fall through to the suffix patterns (_tok_s up, _ms down)
    (r"serving_kernel_.*_over_", "up"),
    # round-21 soak slopes: MiB (or ms) per 10k sessions — growth is
    # the regression, shrinking is the win; RSS final rides along.
    # Verdict/census keys are strings (auto-skipped) and *_frac keys
    # hit the skip list — the soak-smoke gate enforces those exactly.
    (r"serving_soak_rss_slope", "down"),
    (r"serving_soak_host_wall_slope", "down"),
    (r"serving_soak_rss_mib", "down"),
)


def direction(key: str) -> Optional[str]:
    """'up' = higher is better, 'down' = lower is better, None = skip.
    Throughput patterns win over the time-suffix patterns (a *_tok_s key
    is a rate even though it ends in _s)."""
    for pattern, sense in DIRECTION_OVERRIDES:
        if re.search(pattern, key):
            return sense
    for pattern in SKIP_PATTERNS:
        if re.search(pattern, key):
            return None
    if _HIGHER_BETTER.search(key):
        return "up"
    if _LOWER_BETTER.search(key):
        return "down"
    return None


def load_round(path: str) -> dict:
    """A bench dict from either shape: the driver's
    ``{"parsed": {...}}`` envelope or a flat metrics dict."""
    with open(path) as f:
        data = json.load(f)
    if isinstance(data, dict) and isinstance(data.get("parsed"), dict):
        data = data["parsed"]
    if not isinstance(data, dict):
        raise SystemExit(f"{path}: not a bench dict")
    return data


def compare(current: dict, previous: dict,
            overrides: Optional[Dict[str, float]] = None) -> dict:
    """{'regressions': [...], 'improvements': [...], 'within': n,
    'skipped': n} — each regression row carries key, previous, current,
    band, and the relative change."""
    overrides = overrides or {}
    regressions, improvements = [], []
    within = skipped = 0
    # CPU-interpret honesty skip (PR 10 rule, extended to the round-20
    # serving_kernel_* columns): when either round's gather A/B ran off
    # TPU, its pallas-path timings measured the Pallas INTERPRETER —
    # plumbing, not a performance claim — so kernel-variant rows are
    # not gated at all rather than gated against noise.
    interp = (current.get("gather_ab_backend", "tpu") != "tpu"
              or previous.get("gather_ab_backend", "tpu") != "tpu")
    for key in sorted(set(current) & set(previous)):
        cur, prev = current[key], previous[key]
        if (not isinstance(cur, (int, float))
                or not isinstance(prev, (int, float))
                or isinstance(cur, bool) or isinstance(prev, bool)):
            skipped += 1
            continue
        if interp and re.match(r"serving_kernel_", key):
            skipped += 1
            continue
        sense = direction(key)
        if sense is None or prev == 0:
            skipped += 1
            continue
        band = band_for(key, overrides)
        rel = (cur - prev) / abs(prev)
        row = {"key": key, "previous": prev, "current": cur,
               "rel_change": round(rel, 4), "band": band}
        worse = rel < -band if sense == "up" else rel > band
        better = rel > band if sense == "up" else rel < -band
        if worse:
            regressions.append(row)
        elif better:
            improvements.append(row)
        else:
            within += 1
    return {
        "regressions": regressions,
        "improvements": improvements,
        "within": within,
        "skipped": skipped,
    }


def newest_rounds(directory: str) -> Tuple[str, str]:
    rounds = sorted(glob.glob(os.path.join(directory, "BENCH_r[0-9]*.json")))
    if len(rounds) < 2:
        raise SystemExit(
            f"--auto needs >= 2 BENCH_r0N.json files in {directory}, "
            f"found {len(rounds)}"
        )
    return rounds[-1], rounds[-2]


def blocksan_off_nil(emit_json: bool = False) -> int:
    """The blocksan-off overhead gate: prove the sanitizer is detached
    when ``PDT_BLOCKSAN`` is unset (structural nil — each hook site is a
    single is-None branch) and that detached alloc/free cycles are not
    slower than attached ones (generous 1.5x bound: timing is
    informational, the structural checks are the gate)."""
    import time as _time

    os.environ.pop("PDT_BLOCKSAN", None)
    sys.path.insert(0, REPO_DEFAULT)
    from pytorch_distributed_tpu.analysis.blocksan import (
        BlockSanitizer, maybe_sanitizer,
    )
    from pytorch_distributed_tpu.serving.kv_pool import BlockAllocator

    assert maybe_sanitizer() is None, \
        "PDT_BLOCKSAN unset but maybe_sanitizer() armed a sanitizer"
    alloc = BlockAllocator(n_blocks=64)
    assert alloc.sanitizer is None, \
        "fresh BlockAllocator arrived with a sanitizer attached"

    def cycles(a, n=2000):
        t0 = _time.perf_counter()
        for i in range(n):
            a.alloc(1, 4)
            a.free(1)
        return (_time.perf_counter() - t0) / n * 1e9  # ns per cycle

    cycles(alloc, 200)  # warm both paths before timing
    off_ns = cycles(alloc)
    san = BlockSanitizer()
    san.attach(alloc, name="bench")
    cycles(alloc, 200)
    on_ns = cycles(alloc)
    san.assert_clean()
    row = {
        "blocksan_off_ns_per_cycle": round(off_ns),
        "blocksan_on_ns_per_cycle": round(on_ns),
        "blocksan_off_detached": True,
    }
    print(f"blocksan-off: detached (structural nil), "
          f"{row['blocksan_off_ns_per_cycle']} ns/cycle off vs "
          f"{row['blocksan_on_ns_per_cycle']} ns/cycle on")
    if emit_json:
        print(json.dumps(row))
    if off_ns > on_ns * 1.5:
        print(f"blocksan-off: detached path SLOWER than attached "
              f"({off_ns:.0f} ns vs {on_ns:.0f} ns) — hook sites are "
              f"doing work while detached", file=sys.stderr)
        return 1
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("paths", nargs="*",
                   help="CURRENT.json PREVIOUS.json (or use --auto)")
    p.add_argument("--auto", action="store_true",
                   help="compare the two newest BENCH_r0N.json in --dir")
    p.add_argument("--dir", default=REPO_DEFAULT, help="round directory")
    p.add_argument("--band", action="append", default=[],
                   metavar="KEY=FRAC", help="override one key's band")
    p.add_argument("--json", action="store_true",
                   help="append the comparison as one JSON line")
    p.add_argument("--blocksan-off", action="store_true",
                   help="assert the block-lifecycle sanitizer is fully "
                        "detached (nil overhead) when PDT_BLOCKSAN is "
                        "unset, then exit")
    args = p.parse_args(argv)

    if args.blocksan_off:
        return blocksan_off_nil(emit_json=args.json)
    if args.auto:
        cur_path, prev_path = newest_rounds(args.dir)
    elif len(args.paths) == 2:
        cur_path, prev_path = args.paths
    else:
        p.error("pass CURRENT.json PREVIOUS.json, or --auto")
    overrides = {}
    for spec in args.band:
        key, _, frac = spec.partition("=")
        if not frac:
            p.error(f"--band needs KEY=FRAC, got {spec!r}")
        overrides[key] = float(frac)

    result = compare(load_round(cur_path), load_round(prev_path), overrides)
    print(f"bench regression: {os.path.basename(cur_path)} vs "
          f"{os.path.basename(prev_path)}")
    print(f"  within band: {result['within']}, improvements: "
          f"{len(result['improvements'])}, skipped: {result['skipped']}")
    for row in result["improvements"]:
        print(f"  + {row['key']}: {row['previous']} -> {row['current']} "
              f"({row['rel_change']:+.1%})")
    for row in result["regressions"]:
        print(f"  ! REGRESSION {row['key']}: {row['previous']} -> "
              f"{row['current']} ({row['rel_change']:+.1%}, band "
              f"±{row['band']:.0%})")
    if args.json:
        print(json.dumps({
            "bench_regressions": len(result["regressions"]),
            "bench_improvements": len(result["improvements"]),
            "bench_within_band": result["within"],
            "regression_keys": [r["key"] for r in result["regressions"]],
        }))
    return 1 if result["regressions"] else 0


if __name__ == "__main__":
    sys.exit(main())
