"""Measured HBM bytes/step from the profiler's per-memory-space counters.

Closes VERDICT r4 weak #3 / next #8: the ResNet roofline claim previously
rested on an ANALYTIC-MINIMUM byte count (PERF_NOTES §7's >=49%-of-ceiling
lower bound). This derives the ACHIEVED number from the trace itself:

- the Chrome-trace JSON's per-op ``bytes_accessed`` is XLA's cost-model
  figure and DOUBLE-COUNTS on-chip reuse — summing it yields 945 GB/s
  "achieved", above the physically measured 657 GB/s ceiling, proving it
  is not DRAM traffic;
- the ``.xplane.pb`` sidecar carries what the JSON redacts as
  ``memory_access_breakdown: <opaque bytes>``: per-op (operation_type,
  memory_space, bytes) tuples. No xplane proto bindings ship in this
  environment, so this file walks the protobuf WIRE FORMAT generically
  (field numbers verified against the plane's own stat_metadata table:
  31=bytes_accessed, 33=memory_access_breakdown, 24=hlo_category) and
  joins event metadata to per-step execution counts;
- memory_space 1 is HBM (the tsl op_metrics constant; the other observed
  space, 3, matches the S(1) scoped/VMEM annotations on the prefetch
  copies' layouts). Sanity: HBM-only bandwidth must land BELOW the
  measured ceiling, and it does.

Usage: python scripts/trace_hbm.py <trace_dir>   (a jax.profiler.trace
output dir; run e.g. bench.py's ResNet step under the profiler first)
Prints one JSON line: hbm GB/step (read/write), busy ms/step,
achieved GB/s, and %-of-ceiling against the 657 GB/s measured roofline.
"""

from __future__ import annotations

import collections
import glob
import json
import os
import struct
import sys

CEILING_GB_S = 657.0  # measured DRAM ceiling (PERF_NOTES §7)


def _read_varint(buf, i):
    shift = 0
    val = 0
    while True:
        b = buf[i]
        i += 1
        val |= (b & 0x7F) << shift
        if not b & 0x80:
            return val, i
        shift += 7


def _parse(buf):
    out = []
    i, n = 0, len(buf)
    while i < n:
        key, i = _read_varint(buf, i)
        f, wt = key >> 3, key & 7
        if wt == 0:
            v, i = _read_varint(buf, i)
        elif wt == 2:
            ln, i = _read_varint(buf, i)
            v = buf[i:i + ln]
            i += ln
        elif wt == 5:
            v = struct.unpack("<f", buf[i:i + 4])[0]
            i += 4
        elif wt == 1:
            v = struct.unpack("<d", buf[i:i + 8])[0]
            i += 8
        else:
            raise ValueError(f"wire type {wt}")
        out.append((f, wt, v))
    return out


def _try(buf):
    try:
        return _parse(buf)
    except Exception:
        return None


def analyze(trace_dir: str, steps: int) -> dict:
    paths = sorted(glob.glob(
        os.path.join(trace_dir, "plugins/profile/*/*.xplane.pb")
    ))
    if not paths:
        raise FileNotFoundError(f"no xplane.pb under {trace_dir}")
    data = open(paths[-1], "rb").read()

    tpu = None
    for f, wt, v in _parse(data):
        if f == 1 and wt == 2:
            d: dict = {}
            for pf, _pwt, pv in _parse(v):
                d.setdefault(pf, []).append(pv)
            if d.get(2, [b""])[0].startswith(b"/device:TPU"):
                tpu = d
                break
    if tpu is None:
        raise ValueError("no TPU plane in the xplane")

    # Trust-but-verify the hardcoded stat ids against the plane's own
    # metadata table (a profiler version could renumber them).
    stat_names = {}
    for sm in tpu.get(5, []):
        kv = {f: v for f, _wt, v in _parse(sm)}
        md = {f: v for f, _wt, v in _parse(kv[2])}
        stat_names[kv.get(1, md.get(1))] = md.get(2, b"?").decode()
    for sid, want in ((31, "bytes_accessed"),
                      (33, "memory_access_breakdown"),
                      (24, "hlo_category")):
        if stat_names.get(sid) != want:
            raise ValueError(
                f"stat id {sid} is {stat_names.get(sid)!r}, expected "
                f"{want!r} — profiler renumbered; update this parser"
            )

    # event metadata: id -> (breakdown entries, cost-model bytes)
    meta: dict = {}
    for em in tpu.get(4, []):
        kv = {f: v for f, _wt, v in _parse(em)}
        md: dict = {}
        for f, wt, v in _parse(kv[2]):
            md.setdefault(f, []).append((wt, v))
        mid = md.get(1, [(0, kv.get(1))])[0][1]
        brk, ba = [], 0
        for f, vals in md.items():
            if f in (1, 2, 3):
                continue
            for wt, v in vals:
                if wt != 2 or not isinstance(v, bytes):
                    continue
                st = _try(v)
                if not st:
                    continue
                sd = {sf: sv for sf, _swt, sv in st}
                if sd.get(1) == 33:
                    for sf, swt, sv in st:
                        if swt == 2 and sf != 1:
                            for _a, b, c in _try(sv) or []:
                                if b == 2:
                                    ent = {x: z for x, _y, z in
                                           _try(c) or []}
                                    brk.append((ent.get(1), ent.get(2),
                                                ent.get(3, 0)))
                elif sd.get(1) == 31:
                    vals31 = [sv for sf, swt, sv in st
                              if sf != 1 and swt == 0]
                    ba = vals31[0] if vals31 else 0
        meta[mid] = (brk, ba)

    # XLA Ops line: execution counts + busy-time union
    ops_line = None
    for ln in tpu.get(3, []):
        lf = _parse(ln)
        if [v for f, _wt, v in lf if f == 2][0] == b"XLA Ops":
            ops_line = lf
            break
    execs = collections.Counter()
    intervals = []
    for e in [v for f, _wt, v in ops_line if f == 4]:
        ed = {f: v for f, _wt, v in _parse(e)}
        execs[ed.get(1)] += 1
        off, dur = ed.get(2, 0), ed.get(3, 0)
        intervals.append((off, off + dur))
    intervals.sort()
    busy = 0
    cs, ce = intervals[0]
    for s, e2 in intervals[1:]:
        if s > ce:
            busy += ce - cs
            cs, ce = s, e2
        else:
            ce = max(ce, e2)
    busy += ce - cs
    busy_s = busy / 1e12 / steps  # device ps → s

    space = collections.Counter()
    rw = collections.Counter()
    model_bytes = 0
    for mid, cnt in execs.items():
        brk, ba = meta.get(mid, ([], 0))
        model_bytes += ba * cnt
        for otype, sp, byts in brk:
            space[sp] += byts * cnt
            rw[(otype, sp)] += byts * cnt

    hbm = space.get(1, 0) / steps
    out = {
        "hbm_gb_per_step": round(hbm / 1e9, 2),
        "hbm_read_gb": round(rw.get((1, 1), 0) / steps / 1e9, 2),
        "hbm_write_gb": round(rw.get((2, 1), 0) / steps / 1e9, 2),
        "onchip_gb_per_step": round(space.get(3, 0) / steps / 1e9, 2),
        "cost_model_gb_per_step": round(model_bytes / steps / 1e9, 2),
        "busy_ms_per_step": round(busy_s * 1e3, 2),
        "achieved_hbm_gb_s": round(hbm / 1e9 / busy_s, 1),
        "pct_of_ceiling": round(hbm / 1e9 / busy_s / CEILING_GB_S * 100, 1),
    }
    if out["achieved_hbm_gb_s"] > CEILING_GB_S * 1.05:
        raise ValueError(
            f"HBM-space bandwidth {out['achieved_hbm_gb_s']} exceeds the "
            f"measured ceiling {CEILING_GB_S} — the space mapping is "
            "wrong for this profiler version; do not publish"
        )
    return out


if __name__ == "__main__":
    td = sys.argv[1] if len(sys.argv) > 1 else "/tmp/resnet_trace_r5"
    steps = int(sys.argv[2]) if len(sys.argv) > 2 else 5
    print(json.dumps(analyze(td, steps)))
