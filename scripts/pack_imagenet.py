"""Pack an ImageFolder-layout dataset into TPRC splits.

The reference's users get pre-packed ffrecord files on the cluster
(`/public_dataset/1/ImageNet/{train,val}.ffr`, README.md:14-18); this is
the packing tool for this framework's equivalents:

  jpeg mode (default)  train.tprc      JPEG bytes + label (decode at load)
  raw mode             train.rawtprc   pre-decoded uint8 256px (decode-free
                                       fast path, ~10-30x faster loading —
                                       see scripts/bench_data.py)

Input layout: <src>/<class_name>/<image>.{jpg,jpeg,png,...} — classes are
assigned label ids by sorted directory name (torchvision ImageFolder
semantics).

Usage:
  python scripts/pack_imagenet.py <src_dir> <out_dir> --split train [--raw]
  python scripts/pack_imagenet.py <src_dir> <out_dir> --split val --raw --image-size 256
"""

from __future__ import annotations

import argparse
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

EXTS = {".jpg", ".jpeg", ".png", ".bmp", ".webp"}


def iter_images(src: str):
    classes = sorted(
        d for d in os.listdir(src) if os.path.isdir(os.path.join(src, d))
    )
    if not classes:
        raise SystemExit(f"no class directories under {src}")
    print(f"{len(classes)} classes", file=sys.stderr)
    for label, cls in enumerate(classes):
        cdir = os.path.join(src, cls)
        for name in sorted(os.listdir(cdir)):
            if os.path.splitext(name)[1].lower() in EXTS:
                yield os.path.join(cdir, name), label


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("src", help="ImageFolder-layout directory")
    p.add_argument("out", help="output directory for the packed split")
    p.add_argument("--split", default="train", help="split name (file stem)")
    p.add_argument("--raw", action="store_true",
                   help="pre-decode to uint8 (the fast path)")
    p.add_argument("--image-size", type=int, default=256,
                   help="raw mode: stored square size (shorter-side resize "
                        "+ center crop)")
    args = p.parse_args()
    os.makedirs(args.out, exist_ok=True)

    t0 = time.time()
    if args.raw:
        from pytorch_distributed_tpu.data.raw import write_imagenet_raw_split

        path = os.path.join(args.out, f"{args.split}.rawtprc")
        n = write_imagenet_raw_split(
            path,
            ((open(f, "rb").read(), label) for f, label in iter_images(args.src)),
            image_size=args.image_size,
        )
    else:
        from pytorch_distributed_tpu.data.imagenet import write_imagenet_split

        path = os.path.join(args.out, f"{args.split}.tprc")
        n = write_imagenet_split(
            path,
            ((open(f, "rb").read(), label) for f, label in iter_images(args.src)),
        )
    dt = time.time() - t0
    print(f"packed {n} records -> {path} "
          f"({os.path.getsize(path) / 2**20:.0f} MB, {dt:.0f}s)",
          file=sys.stderr)
    from pytorch_distributed_tpu.data.packed_record import PackedRecordReader

    PackedRecordReader(path).verify_all()
    print("integrity sweep OK", file=sys.stderr)


if __name__ == "__main__":
    main()
