"""pdt_top: a live terminal view over the unified telemetry JSONL.

``telemetry_report.py`` is the post-hoc renderer; this is the `top`-style
live twin for a run in flight — tail one or more ``MetricsLogger`` JSONL
streams (a trainer's ``metrics.jsonl``, a server's ``--metrics-out``, a
fleet's shared stream) and re-render an aggregate view every
``--interval`` seconds:

- **train**: last epoch/step/loss, mean step ms over the tail window;
- **goodput**: the latest ledger fractions;
- **serving/fleet**: request + token counts, TTFT / per-token p50/p95
  over the last ``--window`` retirements, per-replica queue depth and
  role from the newest ``fleet_summary``;
- **anomalies**: per-series counts plus the most recent excursion;
- **cost**: the top measured programs by attributed wall (once
  ``kind="program_cost"`` cards exist);
- **inflight** (round 14): requests currently in flight, sourced from
  the lifecycle span stream — roots begun but not yet ended;
- **pressure** (round 14): preempt count/rate and decision mix, parked
  chains from the newest ``fleet_summary``, swap bytes moved and
  aborts, from ``kind="preempt"``/``kind="swap"`` records;
- **resource** (round 21): newest RSS and its live slope against
  cumulative sessions (``kind="resource"`` monitor samples), plus the
  newest census sweep's verdict and worst bound ratio
  (``kind="census"``) — the scale observatory's in-flight view;
- **gateway** (round 22): front-door connection count, live open SSE
  streams and queued ingress (the newest ``kind="http"`` record's
  gauges), 429/400 counters, client disconnects, and the worst
  inter-token stream gap seen over the wire.

Only new bytes are read per refresh (the files are followed, not
re-parsed), so tailing a long run is O(new events). ``--once`` renders
the current state and exits — the testable/scriptable mode. The HTTP
counterpart for scrapers is ``telemetry.export.MetricsExporter``
(``--metrics-port`` on every recipe).

Usage:
    python scripts/pdt_top.py RUN.jsonl [SERVE.jsonl ...] [--interval 2]
    python scripts/pdt_top.py fleet.jsonl --once
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Dict, List

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from pytorch_distributed_tpu.telemetry.latency import (  # noqa: E402
    percentiles,
)


class Tail:
    """Incremental JSONL reader: ``poll()`` returns only new records.
    Tolerates a torn final line (kept pending until its newline lands)
    and a file that does not exist yet."""

    def __init__(self, path: str):
        self.path = path
        self._pos = 0
        self._pending = ""

    def poll(self) -> List[dict]:
        try:
            with open(self.path) as f:
                f.seek(self._pos)
                chunk = f.read()
                self._pos = f.tell()
        except FileNotFoundError:
            return []
        records = []
        buf = self._pending + chunk
        lines = buf.split("\n")
        self._pending = lines[-1]  # "" on a clean newline-terminated tail
        for line in lines[:-1]:
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError:
                continue
        return records


class View:
    """Rolling aggregate state over the record stream."""

    def __init__(self, window: int = 256):
        self.window = window
        self.n_records = 0
        self.last: Dict[str, dict] = {}  # kind -> newest record
        self.requests: List[dict] = []  # tail window of retirements
        self.anomaly_counts: Dict[str, int] = {}
        self.last_anomaly: dict = {}
        self.cost: Dict[str, dict] = {}
        self.sheds = 0
        self.tokens = 0
        # pressure tier counters (kind="preempt"/"swap" records)
        self.preempts = 0
        self.preempt_decisions: Dict[str, int] = {}
        self.swap_bytes = 0
        self.swap_aborts = 0
        # prefix cache (round 17; kind="prefix" per-admission records):
        # lifetime totals plus a tail window for the live hit rate
        self.prefix_admissions = 0
        self.prefix_hits = 0
        self.prefix_covered = 0
        self.prefix_prompt = 0
        self.prefix_cows = 0
        self.recent_prefix: List[dict] = []
        # request-lifecycle spans (kind="span"): open span set and open
        # ROOTS — the live in-flight-requests gauge
        self.open_spans: set = set()
        self.open_roots: set = set()
        self.span_records = 0
        # host resources (round 21; kind="resource"/"census"): tail
        # window of monitor samples for the live RSS slope, plus the
        # newest census sweep's verdict
        self.resources: List[dict] = []
        self.census_violations = 0
        # host–device overlap (round 15; kind="overlap"): newest summary
        # per replica plus a rolling tail of bubbles — busy % and the
        # top recent bubble cause per replica
        self.overlap_summary: Dict[int, dict] = {}
        self.overlap_launches = 0
        self.recent_bubbles: List[dict] = []
        # HTTP front door (round 22; kind="http" per-connection
        # records): lifetime counters plus the newest record's live
        # open/queued gauges and the worst inter-token stream gap
        self.http_conns = 0
        self.http_429 = 0
        self.http_400 = 0
        self.http_disconnects = 0
        self.http_worst_gap_ms = 0.0

    def feed(self, records: List[dict]) -> None:
        for r in records:
            self.n_records += 1
            kind = r.get("kind", "?")
            self.last[kind] = r
            if kind == "request":
                if r.get("rejected"):
                    self.sheds += 1
                else:
                    self.tokens += r.get("new_tokens", 0)
                    self.requests.append(r)
                    if len(self.requests) > self.window:
                        self.requests.pop(0)
            elif kind == "anomaly":
                s = r.get("series", "?")
                self.anomaly_counts[s] = self.anomaly_counts.get(s, 0) + 1
                self.last_anomaly = r
            elif kind == "program_cost":
                self.cost[r["program"]] = r
            elif kind == "preempt":
                self.preempts += 1
                d = r.get("decision", "?")
                self.preempt_decisions[d] = (
                    self.preempt_decisions.get(d, 0) + 1
                )
            elif kind == "swap":
                if r.get("ok"):
                    self.swap_bytes += r.get("bytes", 0)
                else:
                    self.swap_aborts += 1
            elif kind == "prefix":
                self.prefix_admissions += 1
                if r.get("covered", 0) > 0:
                    self.prefix_hits += 1
                self.prefix_covered += r.get("covered", 0)
                self.prefix_prompt += r.get("prompt_len", 0)
                if r.get("cow"):
                    self.prefix_cows += 1
                self.recent_prefix.append(r)
                if len(self.recent_prefix) > self.window:
                    self.recent_prefix.pop(0)
            elif kind == "resource":
                self.resources.append(r)
                if len(self.resources) > self.window:
                    self.resources.pop(0)
            elif kind == "census":
                self.census_violations += r.get("violations", 0)
            elif kind == "overlap":
                ev = r.get("ev")
                if ev == "launch":
                    self.overlap_launches += 1
                elif ev == "summary":
                    self.overlap_summary[r.get("replica", 0)] = r
                elif ev == "bubble":
                    self.recent_bubbles.append(r)
                    if len(self.recent_bubbles) > self.window:
                        self.recent_bubbles.pop(0)
            elif kind == "http":
                self.http_conns += 1
                status = r.get("status", 0)
                if status == 429:
                    self.http_429 += 1
                elif status == 400:
                    self.http_400 += 1
                if r.get("disconnect"):
                    self.http_disconnects += 1
                gap = r.get("gap_max_ms") or 0.0
                if gap > self.http_worst_gap_ms:
                    self.http_worst_gap_ms = gap
            elif kind == "span":
                self.span_records += 1
                key = (r.get("trace"), r.get("span"))
                if r.get("ev") == "begin":
                    self.open_spans.add(key)
                    if not r.get("parent"):
                        self.open_roots.add(key)
                elif r.get("ev") == "end":
                    self.open_spans.discard(key)
                    self.open_roots.discard(key)

    def _top_cause(self, replica: int) -> str:
        """The dominant bubble cause (by gap seconds) in the recent
        window for one replica — the live "what is this replica waiting
        on" cell."""
        by_cause: Dict[str, float] = {}
        for b in self.recent_bubbles:
            if b.get("replica") != replica:
                continue
            c = b.get("cause", "?")
            by_cause[c] = by_cause.get(c, 0.0) + b.get("gap_s", 0.0)
        if not by_cause:
            return ""
        return max(by_cause.items(), key=lambda kv: kv[1])[0]

    # ---- rendering -------------------------------------------------------

    def lines(self) -> List[str]:
        out = [f"pdt_top — {self.n_records} records "
               f"({time.strftime('%H:%M:%S')})"]
        train = self.last.get("train")
        if train:
            loss = train.get("loss")
            out.append(
                f"train    epoch {train.get('epoch')} step "
                f"{train.get('step')}"
                + (f"  loss {loss:.4f}" if loss is not None else "")
            )
        et = self.last.get("epoch_timing")
        if et:
            rate = et.get("tokens_per_s") or et.get("items_per_s")
            out.append(
                f"steps    {et['steps']} @ {et['mean_ms']:.1f} ms"
                + (f"  ({rate:.0f}/s)" if rate else "")
            )
        gp = self.last.get("goodput")
        if gp:
            out.append(
                f"goodput  {gp['goodput_frac']:.3f} productive  "
                f"compile {gp.get('compile_frac', 0.0):.3f}  "
                f"data {gp.get('data_wait_frac', 0.0):.3f}  "
                f"stall {gp.get('stall_frac', 0.0):.3f}"
            )
        if self.requests:
            ttft = percentiles(
                [r["ttft_s"] for r in self.requests if "ttft_s" in r],
                qs=(50, 95),
            )
            gaps = percentiles(
                [g for r in self.requests
                 for g in r.get("token_gaps_s", [])],
                qs=(50, 95),
            )
            line = (f"serving  {len(self.requests)} recent reqs, "
                    f"{self.tokens} tokens, {self.sheds} shed")
            if ttft:
                line += (f"  ttft {ttft['p50'] * 1e3:.1f}/"
                         f"{ttft['p95'] * 1e3:.1f} ms")
            if gaps:
                line += (f"  tok {gaps['p50'] * 1e3:.1f}/"
                         f"{gaps['p95'] * 1e3:.1f} ms")
            out.append(line)
        if self.http_conns:
            # front-door row (round 22): the newest record carries the
            # live open-streams / queued-ingress gauges as extras
            newest = self.last.get("http") or {}
            line = (f"gateway  {self.http_conns} conns, "
                    f"{newest.get('open', 0)} open streams, "
                    f"{newest.get('queued', 0)} queued  "
                    f"429={self.http_429}  400={self.http_400}  "
                    f"disconnects={self.http_disconnects}")
            if self.http_worst_gap_ms:
                line += f"  worst gap {self.http_worst_gap_ms:.1f} ms"
            out.append(line)
        if self.span_records:
            # in-flight = roots begun but not yet ended in the stream —
            # the live gauge the lifecycle traces give for free
            out.append(
                f"inflight {len(self.open_roots)} requests "
                f"({len(self.open_spans)} open spans, "
                f"{self.span_records} span records)"
            )
        if self.preempts or self.swap_bytes:
            served = len(self.requests) + self.sheds
            rate = self.preempts / served if served else 0.0
            fs = self.last.get("fleet_summary") or {}
            parked = fs.get("parked")
            out.append(
                f"pressure {self.preempts} preempts ({rate:.1%})"
                + (f"  parked={parked}" if parked is not None else "")
                + f"  swap {self.swap_bytes / 2**20:.2f} MiB"
                + (f"  aborts={self.swap_aborts}"
                   if self.swap_aborts else "")
                + ("  [" + ", ".join(
                    f"{k}={v}" for k, v in
                    sorted(self.preempt_decisions.items())) + "]"
                   if self.preempt_decisions else "")
            )
        if self.prefix_admissions:
            recent_hits = sum(
                1 for r in self.recent_prefix if r.get("covered", 0) > 0
            )
            out.append(
                f"prefix   {self.prefix_admissions} admissions, "
                f"hit {self.prefix_hits / self.prefix_admissions:.1%}"
                f" (recent {recent_hits}/{len(self.recent_prefix)})  "
                f"covered {self.prefix_covered}/{self.prefix_prompt} tok "
                f"({self.prefix_covered / max(self.prefix_prompt, 1):.0%})"
                + (f"  cow={self.prefix_cows}" if self.prefix_cows else "")
            )
        if self.overlap_summary or self.overlap_launches:
            cells = []
            for rep, s in sorted(self.overlap_summary.items()):
                if rep == -1:
                    # the round-16 union summary: true device
                    # utilization when replicas share a device —
                    # per-replica fractions overlap and must not be
                    # summed (shared-device honesty)
                    cells.append(
                        f"union busy {s.get('busy_frac', 0.0):.0%}"
                    )
                    continue
                top = self._top_cause(rep)
                cells.append(
                    f"r{rep} busy {s.get('busy_frac', 0.0):.0%}"
                    + (f" ({top})" if top else "")
                )
            out.append(
                f"overlap  {self.overlap_launches} launches  "
                + "  ".join(cells)
            )
        if self.resources:
            # live host-resource row (round 21): newest RSS + the slope
            # over the tailed window, regressed against cumulative
            # sessions — the in-flight view of the soak's headline fit
            from pytorch_distributed_tpu.telemetry.scaling import (
                fit_growth,
            )

            newest = self.resources[-1]
            line = (f"resource rss {newest.get('rss_mib', 0.0):.0f} MiB "
                    f"({newest.get('rss_source', '?')})  "
                    f"live {newest.get('live', 0)} / "
                    f"{newest.get('cumulative', 0)} sessions")
            fit = fit_growth(
                [r.get("cumulative", 0) for r in self.resources],
                [r.get("rss_mib", 0.0) for r in self.resources],
                rel_floor=0.005, abs_floor=1.0)
            if fit["verdict"] != "insufficient":
                line += (f"  slope {fit['slope'] * 1e4:+.1f} MiB/10k "
                         f"({fit['verdict']})")
            census = self.last.get("census")
            if census:
                worst = census.get("worst_ratio", 0.0)
                line += (f"  census "
                         + ("ok" if census.get("ok") else "NOT-OK")
                         + (f" worst {census.get('worst_name', '')}"
                            f"={worst:.2f}" if worst else ""))
                if self.census_violations:
                    line += f"  violations={self.census_violations}"
            out.append(line)
        fs = self.last.get("fleet_summary")
        if fs:
            reps = fs.get("replicas", 0)
            per = []
            for i in range(reps):
                role = fs.get(f"r{i}_role", "?")
                q = fs.get(f"r{i}_queue_depth", "?")
                per.append(f"r{i}({role}) q={q}")
            out.append(
                f"fleet    {reps} replicas, "
                f"{fs.get('handoffs', 0)} handoffs, "
                f"shed {fs.get('shed_rate', 0.0):.1%}  " + "  ".join(per)
            )
        if self.anomaly_counts:
            last = self.last_anomaly
            out.append(
                "anomaly  " + ", ".join(
                    f"{s}={n}" for s, n in sorted(self.anomaly_counts.items())
                )
                + (f"  last: {last.get('series')} z={last.get('zscore')}"
                   if last else "")
            )
        measured = sorted(
            (r for r in self.cost.values() if r.get("calls")),
            key=lambda r: -(r.get("total_s") or 0.0),
        )
        for r in measured[:3]:
            mfu = f" mfu {r['mfu']:.4f}" if r.get("mfu") is not None else ""
            bound = f" [{r['bound']}]" if r.get("bound") else ""
            out.append(
                f"cost     {r['program'][:28]}  "
                f"{r.get('mean_s', 0.0) * 1e3:.2f} ms × {r['calls']}"
                f"{mfu}{bound}"
            )
        return out


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("paths", nargs="+", help="telemetry JSONL file(s)")
    p.add_argument("--interval", type=float, default=2.0,
                   help="refresh seconds (follow mode)")
    p.add_argument("--window", type=int, default=256,
                   help="retirements kept for the rolling percentiles")
    p.add_argument("--once", action="store_true",
                   help="render the current state once and exit")
    args = p.parse_args(argv)

    tails = [Tail(path) for path in args.paths]
    view = View(window=args.window)
    while True:
        for tail in tails:
            view.feed(tail.poll())
        text = "\n".join(view.lines())
        if args.once:
            print(text)
            return 0
        # clear + home, then the frame — a plain-terminal live view
        sys.stdout.write("\x1b[2J\x1b[H" + text + "\n")
        sys.stdout.flush()
        try:
            time.sleep(args.interval)
        except KeyboardInterrupt:
            return 0


if __name__ == "__main__":
    sys.exit(main())
