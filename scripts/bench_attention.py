"""Attention kernel bench + on-TPU validation (VERDICT r1 missing #6).

Round 1's flash kernel had only ever run in interpret mode on CPU; this
compiles BOTH Pallas kernels (forward + the round-2 backward pair) for the
real chip, checks numerical parity against the XLA dense/blockwise paths
on-device, and times fwd and fwd+bwd for all three at growing sequence
lengths. Timing follows PERF_NOTES.md: chained in-jit iterations
(differential k2−k1 slope, scalar-fetch sync) — wall-clock through the
tunnel is otherwise meaningless.

Usage: python scripts/bench_attention.py [--quick]
Prints one JSON line per (impl, L) cell plus parity results.
"""

from __future__ import annotations

import functools
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from pytorch_distributed_tpu.ops.attention import (
    blockwise_attention,
    dense_attention,
)
from pytorch_distributed_tpu.ops.flash_attention import flash_attention


def difftime(f, k1=10, k2=110):
    """Slope of wall time vs in-jit trip count: removes the fixed ~95 ms
    tunnel round-trip and dispatch costs. ``f(n)`` must run n chained
    iterations inside one jit (dynamic trip count → single compile).

    Guarded against sub-resolution timings (the r2 bench shipped a 0.0 ms
    / 7.5M-TFLOP row from exactly this failure): the trip-count delta is
    doubled until the measured window exceeds 20 ms, and a slope at the
    floor raises instead of publishing garbage."""

    def measure(k):
        best = 1e9
        for _ in range(3):
            t0 = time.perf_counter()
            float(f(k))
            best = min(best, time.perf_counter() - t0)
        return best

    float(f(k1))  # compile + warm
    t1 = measure(k1)
    for _ in range(8):
        t2 = measure(k2)
        if t2 - t1 > 0.02:
            break
        k2 *= 2  # window too small for the clock/tunnel noise: widen
    slope = (t2 - t1) / (k2 - k1)
    if slope <= 1e-7:
        raise RuntimeError(
            f"sub-resolution timing (window {t2 - t1:.4f}s over {k2 - k1} "
            "trips) — refusing to report a garbage TFLOP/s number"
        )
    return slope


def attn_flops(b, h, l, d, causal):
    # QK^T + PV, fwd; bwd ≈ 2.5x fwd (dQ, dK, dV + recomputed S/P)
    f = 2 * 2 * b * h * l * l * d
    return f / 2 if causal else f


def bench_impl(name, fn, b, h, l, d, causal, mode, quiet=False):
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(b, l, h, d)), jnp.bfloat16)
    k = jnp.asarray(rng.normal(size=(b, l, h, d)), jnp.bfloat16)
    v = jnp.asarray(rng.normal(size=(b, l, h, d)), jnp.bfloat16)

    if mode == "fwd":
        def body_of(q):
            return jnp.sum(fn(q, k, v).astype(jnp.float32))
    else:
        def body_of(q):
            # ALL THREE grads, consumed — argnums=0 alone would let XLA
            # dead-code-eliminate the entire dK/dV kernel
            gq, gk, gv = jax.grad(
                lambda q, k, v: jnp.sum(fn(q, k, v).astype(jnp.float32)),
                argnums=(0, 1, 2),
            )(q, k, v)
            return (jnp.sum(gq.astype(jnp.float32))
                    + jnp.sum(gk.astype(jnp.float32))
                    + jnp.sum(gv.astype(jnp.float32)))

    @jax.jit
    def chained(n):
        def body(i, s):
            # perturb q by the carry so iterations chain; sum the result
            # into the carry so nothing is dead code
            qq = (q.astype(jnp.float32) + s * 1e-30).astype(jnp.bfloat16)
            return s + body_of(qq) * jnp.float32(1e-30)
        return lax.fori_loop(0, n, body, jnp.float32(0))

    dt = difftime(chained)
    fl = attn_flops(b, h, l, d, causal) * (1.0 if mode == "fwd" else 3.5)
    tflops = round(fl / dt / 1e12, 1)
    if not quiet:  # bench.py reuses this and must print ONE json line total
        print(json.dumps({
            "impl": name, "mode": mode, "L": l, "ms": round(dt * 1e3, 3),
            "tflops": tflops,
        }))
    return dt, tflops


def parity_on_device(b=2, h=4, l=512, d=64):
    rng = np.random.default_rng(1)
    q = jnp.asarray(rng.normal(size=(b, l, h, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, l, h, d)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, l, h, d)), jnp.float32)

    out_f = jax.jit(functools.partial(flash_attention, causal=True))(q, k, v)
    out_d = jax.jit(functools.partial(dense_attention, causal=True))(q, k, v)
    fwd_err = float(jnp.max(jnp.abs(out_f - out_d)))

    gf = jax.jit(jax.grad(
        lambda q, k, v: jnp.sum(flash_attention(q, k, v, causal=True) ** 2),
        argnums=(0, 1, 2)))(q, k, v)
    gd = jax.jit(jax.grad(
        lambda q, k, v: jnp.sum(dense_attention(q, k, v, causal=True) ** 2),
        argnums=(0, 1, 2)))(q, k, v)
    bwd_err = max(
        float(jnp.max(jnp.abs(a - b2))) for a, b2 in zip(gf, gd)
    )
    scale_ref = float(jnp.max(jnp.abs(gd[0])))
    print(json.dumps({
        "parity": "flash_vs_dense_on_device",
        "platform": jax.devices()[0].platform,
        "fwd_max_abs_err": round(fwd_err, 6),
        "bwd_max_abs_err": round(bwd_err, 6),
        "bwd_ref_scale": round(scale_ref, 3),
    }))
    # On-TPU tolerance is set by the MXU's default fp32 matmul precision
    # (bf16-decomposed passes, ~1e-3 relative), not by the kernel math —
    # interpret-mode CPU tests (tests/test_attention.py) pin the math to
    # 1e-5. 1% relative here catches real math regressions.
    out_scale = float(jnp.max(jnp.abs(out_d)))
    assert fwd_err < 1e-2 * max(out_scale, 1.0), (fwd_err, out_scale)
    assert bwd_err < 1e-2 * max(scale_ref, 1.0), (bwd_err, scale_ref)


def sweep_bwd(bwd_impl: str = "split"):
    """Round-4 sweep (VERDICT r3 weak #3): the backward kernels' tiling at
    L >= 4096, independent of the forward's (512, 1024). fwdbwd numbers
    include the fixed fwd kernel, so compare rows, not absolutes.
    ``bwd_impl`` is pinned EXPLICITLY (default the r4-era split kernels,
    this sweep's historical subject) because flash_attention's default
    became "fused" in r5 — pass --sweep-bwd-fused to sweep the fused
    kernel's tiling instead."""
    b, h, d = 2, 4, 128
    for l in (4096, 8192):
        rows = []
        for bq in (256, 512, 1024):
            for bk in (512, 1024, 2048):
                fn = functools.partial(
                    flash_attention, causal=True,
                    bwd_block_q=bq, bwd_block_k=bk, bwd_impl=bwd_impl,
                )
                try:
                    dt, tf = bench_impl(
                        f"flash_bwd[{bq},{bk}]", fn, b, h, l, d, True,
                        "fwdbwd",
                    )
                    rows.append((tf, bq, bk))
                except Exception as e:
                    print(json.dumps({"impl": f"flash_bwd[{bq},{bk}]",
                                      "L": l, "error": str(e)[:120]}))
        if rows:
            tf, bq, bk = max(rows)
            print(json.dumps({"sweep_bwd_best": {"L": l, "bwd_block_q": bq,
                                                 "bwd_block_k": bk,
                                                 "bwd_impl": bwd_impl,
                                                 "tflops": tf}}))


def main():
    if "--sweep-bwd" in sys.argv:
        sweep_bwd()
        return
    if "--sweep-bwd-fused" in sys.argv:
        sweep_bwd(bwd_impl="fused")
        return
    quick = "--quick" in sys.argv
    parity_on_device()
    b, h, d = (2, 4, 128)
    lengths = (1024, 2048) if quick else (1024, 2048, 4096, 8192)
    impls = [
        ("flash", functools.partial(flash_attention, causal=True)),
        ("blockwise", functools.partial(blockwise_attention, causal=True,
                                        block_size=512)),
        ("dense", functools.partial(dense_attention, causal=True)),
    ]
    for l in lengths:
        for mode in ("fwd", "fwdbwd"):
            for name, fn in impls:
                if name == "dense" and l > 4096:
                    continue  # O(L^2) HBM materialization
                try:
                    bench_impl(name, fn, b, h, l, d, True, mode)
                except Exception as e:
                    print(json.dumps({"impl": name, "mode": mode, "L": l,
                                      "error": str(e)[:120]}))


if __name__ == "__main__":
    main()
