"""Headline benchmark: ResNet-50/ImageNet training throughput, one chip.

Measures the compiled train step (forward + loss + backward + gradient
combine + SGD update + BN stats — the trainer's hot path) on ResNet-50
bf16 at 224x224, device-resident data, and prints ONE JSON line:

    {"metric": ..., "value": img/s, "unit": "img/s", "vs_baseline": ratio}

Baseline for the ratio: the reference's single-GPU row — 1,281,167 ImageNet
train images / 1786.7849 s per epoch ≈ 717 img/s on one A100-40GB, fp32,
bs 400 (BASELINE.md; result.png). One chip vs one GPU is the honest
single-device comparison; the reference's own best AMP 8-GPU config averages
≈693 img/s per GPU, so vs_baseline ≳ 1 also implies per-chip parity with
their headline config.

Batch size: 128 by default (best measured on v5e; see the sweep comment in
main()), halved automatically on RESOURCE_EXHAUSTED; override with
BENCH_BS. BENCH_TINY=1 runs a toy model for CI/CPU smoke.
"""

from __future__ import annotations

import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

BASELINE_IMG_S = 1_281_167 / 1786.7849  # single-A100 row, BASELINE.md


def build(batch_size: int, tiny: bool):
    from pytorch_distributed_tpu.models import resnet50
    from pytorch_distributed_tpu.models.resnet import BasicBlock, ResNet
    from pytorch_distributed_tpu.ops.optim import sgd_with_weight_decay
    from pytorch_distributed_tpu.parallel import (
        replicated_sharding,
        shard_batch,
        single_device_mesh,
    )
    from pytorch_distributed_tpu.train.state import TrainState
    from pytorch_distributed_tpu.train.step import make_train_step

    image_size = 32 if tiny else 224
    if tiny:
        model = ResNet(stage_sizes=(1, 1), block_cls=BasicBlock, num_classes=100,
                       num_filters=8, dtype=jnp.bfloat16)
    else:
        model = resnet50(dtype=jnp.bfloat16)

    mesh = single_device_mesh()
    tx = sgd_with_weight_decay(0.1, momentum=0.9, weight_decay=1e-4)
    state = TrainState.create(
        model, tx, jax.random.key(0), (1, image_size, image_size, 3)
    )
    state = jax.device_put(state, replicated_sharding(mesh))
    step = make_train_step(mesh)

    rng = np.random.default_rng(0)
    batch = shard_batch(
        mesh,
        {
            "image": rng.normal(size=(batch_size, image_size, image_size, 3)).astype(
                np.float32
            ),
            "label": (rng.integers(0, 100, batch_size)).astype(np.int32),
        },
    )
    return state, step, batch


def run(batch_size: int, tiny: bool, warmup: int = 10, iters: int = 30):
    from pytorch_distributed_tpu.utils.profiling import device_duty_cycle

    state, step, batch = build(batch_size, tiny)
    for _ in range(warmup):
        state, metrics = step(state, batch)
    # Sync by fetching a value: through tunneled TPU runtimes,
    # block_until_ready alone has been observed to return before the device
    # work drains, inflating throughput ~50x. A scalar device_get cannot lie.
    warm_loss = float(metrics["loss"])
    if not np.isfinite(warm_loss):
        raise RuntimeError(f"non-finite warmup loss {warm_loss}")
    t0 = time.perf_counter()
    for _ in range(iters):
        state, metrics = step(state, batch)
    loss = float(metrics["loss"])
    dt = time.perf_counter() - t0
    if not np.isfinite(loss):
        raise RuntimeError(f"non-finite loss {loss}")
    duty = device_duty_cycle(step, state, batch, iters=10)
    return batch_size * iters / dt, duty


def main() -> None:
    tiny = os.environ.get("BENCH_TINY", "") == "1"
    # bs sweep on v5e (2026-07): 128 → 2590 img/s, 256 → 2540, 512 → 2414.
    batch_size = int(os.environ.get("BENCH_BS", "64" if tiny else "128"))
    if batch_size < 1:
        raise ValueError(f"BENCH_BS must be >= 1, got {batch_size}")
    while True:
        try:
            img_s, duty = run(batch_size, tiny)
            break
        except Exception as e:  # XlaRuntimeError isn't a stable import path
            if "RESOURCE_EXHAUSTED" in str(e) and batch_size > 8:
                batch_size //= 2
                continue
            raise
    print(
        json.dumps(
            {
                "metric": "resnet50_imagenet_train_throughput_1chip"
                if not tiny
                else "tiny_resnet_train_throughput_1chip",
                "value": round(img_s, 2),
                "unit": "img/s",
                "vs_baseline": round(img_s / BASELINE_IMG_S, 4),
                "duty_cycle": round(duty, 4),  # ≙ result.png "avg GPU util"
                "batch_size": batch_size,
                "platform": jax.devices()[0].platform,
                "device": str(jax.devices()[0]),
            }
        )
    )


if __name__ == "__main__":
    main()
