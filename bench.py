"""Headline benchmark: ResNet-50/ImageNet training throughput, one chip.

Measures the compiled train step (forward + loss + backward + gradient
combine + SGD update + BN stats — the trainer's hot path) on ResNet-50
bf16 at 224x224, device-resident data, and prints ONE JSON line:

    {"metric": ..., "value": img/s, "unit": "img/s", "vs_baseline": ratio}

Baseline for the ratio: the reference's single-GPU row — 1,281,167 ImageNet
train images / 1786.7849 s per epoch ≈ 717 img/s on one A100-40GB, fp32,
bs 400 (BASELINE.md; result.png). One chip vs one GPU is the honest
single-device comparison; the reference's own best AMP 8-GPU config averages
≈693 img/s per GPU, so vs_baseline ≳ 1 also implies per-chip parity with
their headline config.

Timing method (see PERF_NOTES.md for the round-2 investigation): the
tunneled TPU runtime has ~95 ms host↔device round-trip latency and
``block_until_ready`` does not reliably block, so the loop dispatches all
iterations asynchronously (donated state chains them on device) and syncs
ONCE at the end by fetching the scalar loss; the single round-trip is
subtracted. ``duty_cycle`` is measured from a ``jax.profiler`` trace
(device-busy time / wall), replacing round 1's per-step-sync estimate that
mostly measured tunnel latency.

Extra fields: ``fp32_img_s`` reproduces the reference's single-device fp32
row on the same chip (skip with BENCH_FP32=0); ``step_ms`` is the amortized
per-step wall time of the headline config.

Batch size: 128 by default (sweep on v5e, round 2: 64→2421, 128→2752,
192→2114, 256→2592/2 img/s — 128 is the knee; the step is HBM-bandwidth-
bound, see PERF_NOTES.md), halved automatically on RESOURCE_EXHAUSTED;
override with BENCH_BS. BENCH_TINY=1 runs a toy model for CI/CPU smoke.

scripts/bench_table.py renders the reference's result.png-shaped
single/DP/DDP/AMP comparison table (BENCH_TABLE.md).
"""

from __future__ import annotations

import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

BASELINE_IMG_S = 1_281_167 / 1786.7849  # single-A100 row, BASELINE.md

# Remote-compile / tunnel failures that merit a bounded retry: one HTTP
# 500 from the compile service erased round 5's LM headline number
# (VERDICT r5 ``lm_error``). Markers are matched against str(e) because
# the tunneled runtime surfaces them as opaque XlaRuntimeError text.
_TRANSIENT_MARKERS = (
    "Internal Server Error",
    "HTTP/1.1 500",
    " 500 ",
    "Bad Gateway",
    "Service Unavailable",
    "UNAVAILABLE",
    "DEADLINE_EXCEEDED",
    "Connection reset",
    "Connection aborted",
    "Socket closed",
    "RST_STREAM",
)


def _is_transient(e: BaseException) -> bool:
    msg = str(e)
    if "RESOURCE_EXHAUSTED" in msg:
        return False  # real OOM: handled by batch halving, never retried
    return any(m in msg for m in _TRANSIENT_MARKERS)


def retry_transient(fn, *args, what: str = "", retries: int = 2,
                    base_delay: float = 2.0, max_delay: float = 10.0,
                    **kwargs):
    """Bounded retry for transient remote-compile/tunnel errors, on the
    deterministic ``resilience.retry`` backoff schedule (seeded jitter —
    reproducible sleeps). Non-transient failures propagate immediately;
    after the last retry the original error propagates, so a section's
    ``*_error`` reporting still works."""
    import sys

    from pytorch_distributed_tpu.resilience.retry import backoff_delays

    delays = backoff_delays(retries=retries, base_delay=base_delay,
                            max_delay=max_delay)
    for attempt in range(len(delays) + 1):
        try:
            return fn(*args, **kwargs)
        except Exception as e:
            if attempt >= len(delays) or not _is_transient(e):
                raise
            print(
                f"bench: {what or getattr(fn, '__name__', 'call')} hit a "
                f"transient error ({str(e)[:160]}); retry "
                f"{attempt + 1}/{len(delays)} in {delays[attempt]:.1f}s",
                file=sys.stderr,
            )
            time.sleep(delays[attempt])


def measure_roundtrip_s(n: int = 3) -> float:
    """Host↔device round-trip cost of one scalar value fetch.

    ~95 ms through the axon tunnel, ~0 on a local backend; measured rather
    than hardcoded so the subtraction below never corrupts local runs.
    """
    x = jnp.zeros(())
    f = jax.jit(lambda v: v + 1)
    float(f(x))  # compile
    best = float("inf")
    for _ in range(n):
        t0 = time.perf_counter()
        float(f(x))
        best = min(best, time.perf_counter() - t0)
    return best


def build(batch_size: int, tiny: bool, dtype=jnp.bfloat16, mesh=None,
          fused: bool = False, int8_trunk: bool = False):
    """State/step/batch for a bench run. ``batch_size`` is the GLOBAL batch
    (sharded over the mesh's data axis; a 1-device mesh makes it per-chip).
    ``mesh`` defaults to one device; scripts/bench_table.py passes multi-
    device meshes to exercise the DP rows with the same timing method."""
    from pytorch_distributed_tpu.models import resnet50
    from pytorch_distributed_tpu.models.resnet import BasicBlock, ResNet
    from pytorch_distributed_tpu.ops.optim import sgd_with_weight_decay
    from pytorch_distributed_tpu.parallel import (
        replicated_sharding,
        shard_batch,
        single_device_mesh,
    )
    from pytorch_distributed_tpu.train.state import TrainState
    from pytorch_distributed_tpu.train.step import make_train_step

    image_size = 32 if tiny else 224
    if tiny:
        model = ResNet(stage_sizes=(1, 1), block_cls=BasicBlock, num_classes=100,
                       num_filters=8, dtype=dtype)
    else:
        model = resnet50(dtype=dtype, fused_bottleneck=fused,
                         int8_trunk=int8_trunk)

    if mesh is None:
        mesh = single_device_mesh()
    tx = sgd_with_weight_decay(0.1, momentum=0.9, weight_decay=1e-4)
    state = TrainState.create(
        model, tx, jax.random.key(0), (1, image_size, image_size, 3)
    )
    state = jax.device_put(state, replicated_sharding(mesh))
    step = make_train_step(mesh)

    rng = np.random.default_rng(0)
    batch = shard_batch(
        mesh,
        {
            "image": rng.normal(size=(batch_size, image_size, image_size, 3)).astype(
                np.float32
            ),
            "label": (rng.integers(0, 100, batch_size)).astype(np.int32),
        },
    )
    return state, step, batch


def run(batch_size: int, tiny: bool, dtype=jnp.bfloat16, warmup: int = 8,
        iters: int = 30, measure_duty: bool = True, mesh=None,
        fused: bool = False, int8_trunk: bool = False):
    from pytorch_distributed_tpu.utils.profiling import device_duty_cycle

    state, step, batch = build(batch_size, tiny, dtype, mesh=mesh, fused=fused,
                               int8_trunk=int8_trunk)
    for _ in range(warmup):
        state, metrics = step(state, batch)
    # Sync by fetching a value: through tunneled TPU runtimes,
    # block_until_ready alone has been observed to return before the device
    # work drains, inflating throughput ~50x. A scalar device_get cannot lie.
    warm_loss = float(metrics["loss"])
    if not np.isfinite(warm_loss):
        raise RuntimeError(f"non-finite warmup loss {warm_loss}")
    t0 = time.perf_counter()
    for _ in range(iters):
        state, metrics = step(state, batch)
    loss = float(metrics["loss"])
    dt = time.perf_counter() - t0
    if not np.isfinite(loss):
        raise RuntimeError(f"non-finite loss {loss}")
    # One value-fetch round-trip sits in the window; subtract the measured
    # cost, but never more than half the window (guards tiny/fast runs).
    dt = max(dt - measure_roundtrip_s(), dt / 2)
    duty = float("nan")
    if measure_duty:
        duty = device_duty_cycle(step, state, batch, iters=min(iters, 20))
    return batch_size * iters / dt, dt / iters, duty


def bench_flash_attention(l: int = 4096) -> dict:
    """Pallas flash fwd+bwd vs XLA blockwise at one LM-shaped config
    (causal, B2 H4 D128) — the headline kernel comparison; the full sweep
    incl. dense and more lengths lives in scripts/bench_attention.py."""
    import functools
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                    "scripts"))
    import bench_attention as ba

    from pytorch_distributed_tpu.ops.attention import blockwise_attention
    from pytorch_distributed_tpu.ops.flash_attention import flash_attention

    b, h, d = 2, 4, 128
    out = {}
    for name, fn in (
        ("flash", functools.partial(flash_attention, causal=True)),
        ("blockwise", functools.partial(blockwise_attention, causal=True,
                                        block_size=512)),
    ):
        _, tflops = ba.bench_impl(name, fn, b, h, l, d, True, "fwdbwd",
                                  quiet=True)
        out[f"attn_{name}_fwdbwd_tflops"] = tflops
    out["attn_len"] = l
    return out


def bench_lm_training() -> dict:
    """GPT-2-small-shaped LM train step with flash attention: the
    capability-beyond-parity headline (tokens/s, MFU). Full config sweep in
    scripts/bench_lm.py; ~51% MFU measured on v5e at L=1024 (BENCH_LM.md)."""
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                    "scripts"))
    import bench_lm

    r = bench_lm.bench("flash", batch=8, seq=1024, iters=10, quiet=True)
    return {
        "lm_tokens_per_s": r["tokens_per_s"],
        "lm_tokens_per_s_min": r["tokens_per_s_min"],
        "lm_tokens_per_s_max": r["tokens_per_s_max"],
        "lm_mfu": r["mfu"],
        "lm_params_m": r["params_m"],
        "lm_attention": "flash",
    }


def bench_data_pipeline(n: int = 2048) -> dict:
    """Host input-pipeline throughput: the raw fast path (RawImageNet,
    uint8, random-crop aug) through the real DataLoader. Measured per host
    core so the number transfers to real pod hosts; scripts/bench_data.py
    has the full per-stage breakdown (JPEG vs raw, reader, H2D)."""
    import tempfile

    from pytorch_distributed_tpu.data.loader import DataLoader
    from pytorch_distributed_tpu.data.raw import RawImageNet, write_imagenet_raw_split

    cache = os.path.join(tempfile.gettempdir(), f"pdt_bench_raw_{n}")
    path = os.path.join(cache, "train.rawtprc")
    if not os.path.exists(path):
        os.makedirs(cache, exist_ok=True)
        rng = np.random.default_rng(0)
        write_imagenet_raw_split(
            path,
            ((rng.integers(0, 255, (256, 256, 3)).astype(np.uint8), i % 1000)
             for i in range(n)),
        )
    workers = os.cpu_count() or 1
    loader = DataLoader(RawImageNet("train", data_dir=cache, aug="crop"),
                        batch_size=128, num_workers=workers, prefetch=4)
    from pytorch_distributed_tpu.data.loader import measure_throughput

    img_s = measure_throughput(loader, epochs=2)
    return {
        "data_pipeline_img_s": round(img_s, 1),
        "data_pipeline_img_s_per_core": round(img_s / workers, 1),
        "data_pipeline_mode": "raw_uint8_crop",
        "host_cores": workers,
    }


def main() -> None:
    tiny = os.environ.get("BENCH_TINY", "") == "1"
    batch_size = int(os.environ.get("BENCH_BS", "64" if tiny else "128"))
    if batch_size < 1:
        raise ValueError(f"BENCH_BS must be >= 1, got {batch_size}")
    fused = os.environ.get("BENCH_FUSED", "1") == "1" and not tiny
    while True:
        try:
            img_s, step_s, duty = retry_transient(
                run, batch_size, tiny, fused=fused, what="headline resnet"
            )
            break
        except Exception as e:  # XlaRuntimeError isn't a stable import path
            if "RESOURCE_EXHAUSTED" in str(e) and batch_size > 8:
                batch_size //= 2
                continue
            raise
    record = {
        "metric": "resnet50_imagenet_train_throughput_1chip"
        if not tiny
        else "tiny_resnet_train_throughput_1chip",
        "value": round(img_s, 2),
        "unit": "img/s",
        "vs_baseline": round(img_s / BASELINE_IMG_S, 4),
        "batch_size": batch_size,
        "step_ms": round(step_s * 1e3, 2),
        "fused_bottleneck": fused,
        "platform": jax.devices()[0].platform,
        "device": str(jax.devices()[0]),
    }
    if np.isfinite(duty):
        record["duty_cycle"] = round(duty, 4)
    # host-only data measurement FIRST: the attention section's jax
    # machinery leaves background CPU load that depresses host-side numbers
    if not tiny and os.environ.get("BENCH_DATA", "1") == "1":
        try:
            record.update(bench_data_pipeline())
        except Exception as e:
            record["data_pipeline_error"] = str(e)[:200]
    if not tiny and os.environ.get("BENCH_CKPT", "1") == "1":
        try:
            import subprocess
            import sys as _sys

            env = {k: v for k, v in os.environ.items()
                   if k not in ("XLA_FLAGS",)}
            env["JAX_PLATFORMS"] = "cpu"
            out = subprocess.run(
                [_sys.executable,
                 os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "scripts", "bench_checkpoint.py")],
                env=env, capture_output=True, text=True, timeout=600,
            )
            record.update(json.loads(out.stdout.strip().splitlines()[-1]))
        except Exception as e:
            record["ckpt_bench_error"] = str(e)[:200]
    if not tiny and os.environ.get("BENCH_ATTN", "1") == "1":
        try:
            record.update(
                retry_transient(bench_flash_attention, what="flash bench")
            )
        except Exception as e:
            record["flash_attn_error"] = str(e)[:200]
    if not tiny and os.environ.get("BENCH_LM", "1") == "1":
        try:
            # bounded retry: round 5 lost this exact headline to ONE
            # transient remote-compile HTTP 500 (VERDICT r5 lm_error)
            record.update(retry_transient(bench_lm_training, what="lm bench"))
        except Exception as e:
            record["lm_error"] = str(e)[:200]
    if not tiny and os.environ.get("BENCH_SERVING", "1") == "1":
        try:
            import sys as _sys

            _sys.path.insert(0, os.path.join(
                os.path.dirname(os.path.abspath(__file__)), "scripts"))
            import bench_serving

            def _serving():
                r = bench_serving.measure(slots=32, max_new=64)
                r.pop("device", None)
                # admission-heavy A/B: the dense layout's per-admission
                # stall vs the paged engine's, both folded into the
                # equilibrium short-output throughput model
                r.update(bench_serving.measure_admission_stall(
                    slots=32, tick_ms=r["serving_decode_ms_per_token"]
                ))
                r.update(bench_serving.measure_paged_admission(
                    slots=32, tick_ms=r["serving_decode_ms_per_token"]
                ))
                return r

            record.update(retry_transient(_serving, what="serving bench"))
        except Exception as e:
            record["serving_error"] = str(e)[:200]
    if not tiny and os.environ.get("BENCH_FLEET", "1") == "1":
        try:
            import sys as _sys

            _sys.path.insert(0, os.path.join(
                os.path.dirname(os.path.abspath(__file__)), "scripts"))
            import bench_serving

            def _fleet():
                # round-10 fleet A/Bs on the stock bursty heavy-tail
                # trace: 1-vs-2-replica within-SLO goodput, colocated-
                # vs-disaggregated decode tick p95 (tiny model — the
                # router simulation measures scheduling, not FLOPs)
                r = bench_serving.measure_fleet()
                r.update(bench_serving.measure_disagg())
                r.pop("device", None)
                return r

            record.update(retry_transient(_fleet, what="fleet bench"))
        except Exception as e:
            record["fleet_error"] = str(e)[:200]
    if not tiny and os.environ.get("BENCH_FP32", "1") == "1":
        fp32_bs = batch_size
        while True:
            try:
                fp32_img_s, _, _ = run(fp32_bs, tiny, dtype=jnp.float32,
                                       measure_duty=False)
                record["fp32_img_s"] = round(fp32_img_s, 2)
                record["fp32_vs_baseline"] = round(fp32_img_s / BASELINE_IMG_S, 4)
                record["fp32_batch_size"] = fp32_bs
                break
            except Exception as e:
                # fp32 needs ~2x the HBM of bf16; never lose the already-
                # measured headline number to an fp32 OOM.
                if "RESOURCE_EXHAUSTED" in str(e) and fp32_bs > 8:
                    fp32_bs //= 2
                    continue
                record["fp32_error"] = str(e)[:200]
                break
    print(json.dumps(record))


if __name__ == "__main__":
    main()
